"""Shape/layout manipulation ops (reference: python/paddle/tensor/
manipulation.py; phi reshape/concat/split/... kernels + stride/ view kernels).
Views are value-semantics here: XLA aliases buffers where it can, so "view"
ops are metadata-only after compilation.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, as_tensor
from .registry import register

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze", "unsqueeze_",
    "transpose", "moveaxis", "swapaxes", "concat", "stack", "unstack", "split",
    "tensor_split", "chunk", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "flip", "rot90", "roll", "repeat_interleave", "gather",
    "gather_nd", "scatter", "scatter_add", "scatter_nd_add", "put_along_axis",
    "take_along_axis",
    "index_select", "index_sample", "index_add", "index_put", "masked_select",
    "masked_fill", "slice", "strided_slice", "crop", "pad", "unbind", "numel",
    "shard_index", "as_real", "as_complex", "view", "view_as", "unfold",
    "tensordot", "atleast_1d", "atleast_2d", "atleast_3d", "diagonal",
    "diag_embed", "kron", "take", "select_scatter", "slice_scatter",
]


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        # a Tensor-valued target shape must become python ints to build
        # the STATIC output shape XLA requires — the read IS the
        # host/graph boundary (reference kernels read the shape tensor
        # on host the same way); inside a trace, pass a python list
        return tuple(int(v) for v in np.asarray(shape._data))  # tpulint: disable=TPU103,TPU104 — static-shape construction from a shape tensor: host by design
    return tuple(int(v.item()) if isinstance(v, Tensor) else int(v) for v in shape)


@register("reshape", category="manipulation")
def reshape(x, shape, name=None):
    """View with a new shape, one dim inferrable as -1 (reference
    paddle.reshape)."""
    shape = _norm_shape(shape)
    return dispatch.call("reshape", lambda a: jnp.reshape(a, shape), [_t(x)])


def reshape_(x, shape, name=None):
    """In-place reshape: swaps the payload view (reference paddle.reshape_)."""
    out = reshape(x, shape)
    x._swap_payload(out._data)
    x.grad_node, x.output_index, x.stop_gradient = out.grad_node, out.output_index, out.stop_gradient
    return x


def view(x, shape_or_dtype, name=None):
    """Reinterpret shape (or dtype) without copy semantics (reference
    paddle.view)."""
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    d = convert_dtype(shape_or_dtype)
    return dispatch.call("view_dtype", lambda a: a.view(d), [_t(x)])


def view_as(x, other, name=None):
    """view() to the shape of ``other`` (reference paddle.view_as)."""
    return reshape(x, other.shape)


@register("flatten", category="manipulation")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    """Collapse dims [start_axis, stop_axis] into one (reference
    paddle.flatten)."""
    xt = _t(x)
    nd = xt.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    def f(a):
        if a.ndim == 0:
            return a.reshape(1)
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return a.reshape(new_shape)
    return dispatch.call("flatten", f, [xt],
                         export_attrs={"start_axis": s, "stop_axis": e})


@register("squeeze", category="manipulation")
def squeeze(x, axis=None, name=None):
    """Drop size-1 dims, all or listed (reference paddle.squeeze)."""
    xt = _t(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % max(xt.ndim, 1) for a in axes if xt.shape[a] == 1)
    return dispatch.call("squeeze", lambda a: jnp.squeeze(a, axis=ax), [xt])


def squeeze_(x, axis=None, name=None):
    """In-place squeeze (reference paddle.squeeze_)."""
    out = squeeze(x, axis)
    x._swap_payload(out._data)
    x.grad_node, x.output_index = out.grad_node, out.output_index
    return x


@register("unsqueeze", category="manipulation")
def unsqueeze(x, axis, name=None):
    """Insert size-1 dims at ``axis`` (reference paddle.unsqueeze)."""
    axes = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    axes = tuple(int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes)
    return dispatch.call("unsqueeze", lambda a: jnp.expand_dims(a, axes), [_t(x)])


def unsqueeze_(x, axis, name=None):
    """In-place unsqueeze (reference paddle.unsqueeze_)."""
    out = unsqueeze(x, axis)
    x._swap_payload(out._data)
    x.grad_node, x.output_index = out.grad_node, out.output_index
    return x


@register("transpose", category="manipulation")
def transpose(x, perm=None, name=None):
    """Permute dims by ``perm`` (reference paddle.transpose)."""
    xt = _t(x)
    if perm is None:
        perm = tuple(reversed(range(xt.ndim)))
    perm = tuple(int(p) for p in perm)
    return dispatch.call("transpose", lambda a: jnp.transpose(a, perm), [xt])


def moveaxis(x, source, destination, name=None):
    """Move dims from source to destination positions (reference
    paddle.moveaxis)."""
    return dispatch.call("moveaxis", lambda a: jnp.moveaxis(a, source, destination), [_t(x)])


def swapaxes(x, axis0, axis1, name=None):
    """Exchange two dims (reference paddle.swapaxes)."""
    return dispatch.call("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), [_t(x)])


@register("concat", category="manipulation")
def concat(x: Sequence, axis=0, name=None):
    """Join tensors along an existing axis (reference paddle.concat)."""
    ts = [_t(v) for v in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return dispatch.call("concat", lambda *xs: jnp.concatenate(xs, axis=axis), ts)


@register("stack", category="manipulation")
def stack(x: Sequence, axis=0, name=None):
    """Join tensors along a NEW axis (reference paddle.stack)."""
    ts = [_t(v) for v in x]
    return dispatch.call("stack", lambda *xs: jnp.stack(xs, axis=axis), ts)


def unstack(x, axis=0, num=None, name=None):
    """Split along ``axis`` into that dim's tensors (reference paddle.unstack).
    """
    xt = _t(x)
    n = num or xt.shape[axis]
    outs = dispatch.call(
        "unstack",
        lambda a: tuple(jnp.squeeze(s, axis=axis)
                        for s in jnp.split(a, n, axis=axis)), [xt])
    return list(outs)


@register("split", category="manipulation")
def split(x, num_or_sections, axis=0, name=None):
    """Split into sections (count or sizes) along ``axis`` (reference
    paddle.split)."""
    xt = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = axis % xt.ndim
    if isinstance(num_or_sections, int):
        outs = dispatch.call("split",
                             lambda a: tuple(jnp.split(a, num_or_sections, axis=ax)), [xt])
    else:
        secs = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        total = xt.shape[ax]
        if any(s == -1 for s in secs):
            rem = total - sum(s for s in secs if s != -1)
            secs = [rem if s == -1 else s for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        outs = dispatch.call("split", lambda a: tuple(jnp.split(a, idx, axis=ax)), [xt])
    return list(outs)


def tensor_split(x, num_or_indices, axis=0, name=None):
    """Split into n parts allowing uneven tails (reference
    paddle.tensor_split)."""
    xt = _t(x)
    outs = dispatch.call("tensor_split",
                         lambda a: tuple(jnp.array_split(a, num_or_indices, axis=axis)), [xt])
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    """Split into ``chunks`` equal parts along ``axis`` (reference
    paddle.chunk)."""
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    """Remove ``axis`` and return its slices (reference paddle.unbind)."""
    return unstack(x, axis)


@register("tile", category="manipulation")
def tile(x, repeat_times, name=None):
    """Repeat the whole tensor per-dim ``repeat_times`` (reference
    paddle.tile)."""
    reps = _norm_shape(repeat_times)
    return dispatch.call("tile", lambda a: jnp.tile(a, reps), [_t(x)])


@register("expand", category="manipulation")
def expand(x, shape, name=None):
    """Broadcast size-1 dims up to ``shape`` without copying semantics
    (reference paddle.expand)."""
    xt = _t(x)
    shape = list(_norm_shape(shape))
    cur = [1] * (len(shape) - xt.ndim) + list(xt.shape)
    tgt = [c if s == -1 else s for s, c in zip(shape, cur)]
    return dispatch.call("expand", lambda a: jnp.broadcast_to(a, tuple(tgt)), [xt])


def expand_as(x, y, name=None):
    """Broadcast ``x`` to the shape of ``y`` (reference paddle.expand_as)."""
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    """Broadcast to an explicit ``shape`` (reference paddle.broadcast_to)."""
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    """Broadcast a list of tensors to their common shape (reference
    paddle.broadcast_tensors)."""
    ts = [_t(v) for v in inputs]
    outs = dispatch.call("broadcast_tensors",
                         lambda *xs: tuple(jnp.broadcast_arrays(*xs)), ts)
    return list(outs)


@register("flip", category="manipulation")
def flip(x, axis, name=None):
    """Reverse order along listed axes (reference paddle.flip)."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return dispatch.call("flip", lambda a: jnp.flip(a, axis=ax), [_t(x)])


def rot90(x, k=1, axes=(0, 1), name=None):
    """Rotate in the plane of two axes by k*90 degrees (reference
    paddle.rot90)."""
    return dispatch.call("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), [_t(x)])


@register("roll", category="manipulation")
def roll(x, shifts, axis=None, name=None):
    """Circularly shift elements along axes (reference paddle.roll)."""
    return dispatch.call("roll", lambda a: jnp.roll(a, shifts, axis=axis), [_t(x)])


def repeat_interleave(x, repeats, axis=None, name=None):
    """Repeat each element ``repeats`` times along ``axis`` (reference
    paddle.repeat_interleave)."""
    if isinstance(repeats, Tensor):
        # per-element repeat counts: the output length is sum(repeats)
        # — a data-dependent shape jit cannot capture, so the counts
        # are read on host (jnp.repeat would need a host-known
        # total_repeat_length either way)
        reps = np.asarray(repeats._data)  # tpulint: disable=TPU104 — data-dependent output size: host by design
        return dispatch.call("repeat_interleave",
                             lambda a: jnp.repeat(a, reps, axis=axis), [_t(x)])
    return dispatch.call("repeat_interleave",
                         lambda a: jnp.repeat(a, repeats, axis=axis), [_t(x)])


# ----------------------------------------------------------- gather/scatter
@register("gather", category="indexing")
def gather(x, index, axis=0, name=None):
    """Select rows of ``x`` by 1D ``index`` along ``axis`` (reference
    paddle.gather)."""
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return dispatch.call("gather", lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis),
                         [_t(x), _t(index)], differentiable_mask=[True, False])


@register("gather_nd", category="indexing")
def gather_nd(x, index, name=None):
    """Gather slices by multi-dim index tuples (reference paddle.gather_nd)."""
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = a[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return dispatch.call("gather_nd", f, [_t(x), _t(index)],
                         differentiable_mask=[True, False])


@register("scatter", category="indexing")
def scatter(x, index, updates, overwrite=True, name=None):
    """Write ``updates`` rows into ``x`` at ``index`` (overwrite or add)
    (reference paddle.scatter)."""
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return dispatch.call("scatter", f, [_t(x), _t(index), _t(updates)],
                         differentiable_mask=[True, False, True])


@register("scatter_add", category="indexing")
def scatter_add(x, index, updates, name=None):
    """Accumulate ``updates`` rows into ``x`` at ``index`` along dim 0
    (duplicate indices sum — torch.scatter_add over rows; the sharded-
    embedding backward's table-grad op). Unlike ``scatter``, duplicates
    never overwrite: out[index[i]] += updates[i].

    The op traces as ``scatter_add`` so the planner prices the
    row-scatter traffic and the spmd rule keeps the destination's
    (possibly vocab-sharded) placement — see
    ``distributed/spmd/rules.py:scatter_add_rule``.
    """
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32).reshape(-1)
        return a.at[idx].add(upd.astype(a.dtype))
    return dispatch.call("scatter_add", f, [_t(x), _t(index), _t(updates)],
                         differentiable_mask=[True, False, True])


@register("scatter_nd_add", category="indexing")
def scatter_nd_add(x, index, updates, name=None):
    """Add ``updates`` into zeros/x at multi-dim indices (reference
    paddle.scatter_nd_add)."""
    def f(a, idx, upd):
        idx = idx.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return dispatch.call("scatter_nd_add", f, [_t(x), _t(index), _t(updates)],
                         differentiable_mask=[True, False, True])


def scatter_nd(index, updates, shape, name=None):
    z = Tensor(jnp.zeros(_norm_shape(shape), dtype=_t(updates)._data.dtype))
    return scatter_nd_add(z, index, updates)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    """Gather values along an axis by same-rank index (reference
    paddle.take_along_axis)."""
    return dispatch.call("take_along_axis",
                         lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
                         [_t(arr), _t(indices)], differentiable_mask=[True, False])


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    """Scatter values along an axis by index (assign/add/mul reduce) (reference
    paddle.put_along_axis)."""
    def f(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        dims = tuple(range(a.ndim))
        if reduce == "assign":
            # emulate via scatter on flattened index grid
            idx = jnp.indices(i.shape)
            full_idx = tuple(idx[d] if d != axis % a.ndim else i for d in dims)
            return a.at[full_idx].set(v)
        idx = jnp.indices(i.shape)
        full_idx = tuple(idx[d] if d != axis % a.ndim else i for d in dims)
        if reduce in ("add", "sum"):
            return a.at[full_idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[full_idx].multiply(v)
        raise ValueError(f"unsupported reduce {reduce}")
    return dispatch.call("put_along_axis", f, [_t(arr), _t(indices), _t(values)],
                         differentiable_mask=[True, False, True])


@register("index_select", category="indexing")
def index_select(x, index, axis=0, name=None):
    """Select entries along ``axis`` by 1D index (reference
    paddle.index_select)."""
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    """Per-row gather: out[i, j] = x[i, index[i, j]] (reference
    paddle.index_sample)."""
    return dispatch.call(
        "index_sample",
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
        [_t(x), _t(index)], differentiable_mask=[True, False])


def index_add(x, index, axis, value, name=None):
    """Add ``value`` rows at ``index`` along ``axis`` (reference
    paddle.index_add)."""
    def f(a, i, v):
        i = i.astype(jnp.int32)
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return dispatch.call("index_add", f, [_t(x), _t(index), _t(value)],
                         differentiable_mask=[True, False, True])


def index_put(x, indices, value, accumulate=False, name=None):
    """Scatter values at a tuple of index tensors (reference paddle.index_put).
    """
    idx_ts = [_t(i) for i in indices]
    def f(a, v, *idx):
        idx = tuple(i.astype(jnp.int32) if np.issubdtype(np.dtype(i.dtype), np.integer)
                    else i for i in idx)
        if accumulate:
            return a.at[idx].add(v)
        return a.at[idx].set(v)
    return dispatch.call("index_put", f, [_t(x), _t(value)] + idx_ts,
                         differentiable_mask=[True, True] + [False] * len(idx_ts))


def take(x, index, mode="raise", name=None):
    """Gather from the FLATTENED tensor by integer index, with mode (reference
    paddle.take)."""
    return dispatch.call("take",
                         lambda a, i: jnp.take(a.reshape(-1), i.astype(jnp.int32),
                                               mode="clip" if mode == "clip" else "wrap"),
                         [_t(x), _t(index)], differentiable_mask=[True, False])


@register("masked_select", category="indexing", differentiable=False)
def masked_select(x, mask, name=None):
    # Dynamic output size — host-side (not jit-capturable; reference kernel is
    # likewise dynamic). Returns a 1-D tensor of the selected elements.
    """1D tensor of elements where mask is True (host path: dynamic output
    shape) (reference paddle.masked_select)."""
    xt, mt = _t(x), _t(mask)
    data = np.asarray(xt._data)[np.asarray(mt._data).astype(bool)]  # tpulint: disable=TPU104 — mask population count IS the output shape: host by design (see op docstring)
    return Tensor(jnp.asarray(data))


def masked_fill(x, mask, value, name=None):
    """Set elements where mask is True to ``value`` (reference
    paddle.masked_fill)."""
    v = value.item() if isinstance(value, Tensor) else value
    return dispatch.call("masked_fill",
                         lambda a, m: jnp.where(m.astype(bool), jnp.asarray(v, dtype=a.dtype), a),
                         [_t(x), _t(mask)], differentiable_mask=[True, False])


# ------------------------------------------------------------------- slicing
import builtins
builtins_slice = builtins.slice


@register("slice", category="manipulation")
def slice(x, axes, starts, ends, name=None):
    """Extract [starts, ends) along ``axes`` (reference paddle.slice)."""
    xt = _t(x)
    sl = [builtins_slice(None)] * xt.ndim
    for ax, st, en in zip(axes, starts, ends):
        st = int(st.item()) if isinstance(st, Tensor) else int(st)
        en = int(en.item()) if isinstance(en, Tensor) else int(en)
        sl[ax] = builtins_slice(st, en)
    sl = tuple(sl)
    return dispatch.call("slice", lambda a: a[sl], [xt])


def strided_slice(x, axes, starts, ends, strides, name=None):
    """Slice with explicit strides per axis (reference paddle.strided_slice).
    """
    xt = _t(x)
    sl = [builtins_slice(None)] * xt.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        sl[ax] = builtins_slice(int(st), int(en), int(sd))
    sl = tuple(sl)
    return dispatch.call("strided_slice", lambda a: a[sl], [xt])


def crop(x, shape=None, offsets=None, name=None):
    """Crop a box of ``shape`` at ``offsets`` (reference paddle.crop)."""
    xt = _t(x)
    shape = _norm_shape(shape)
    offsets = _norm_shape(offsets) if offsets is not None else (0,) * xt.ndim
    sl = tuple(builtins_slice(o, o + s if s != -1 else None)
               for o, s in zip(offsets, shape))
    return dispatch.call("crop", lambda a: a[sl], [xt])


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write ``value`` into a strided slice (reference paddle.slice_scatter).
    """
    def f(a, v):
        sl = [builtins_slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            sl[ax] = builtins_slice(int(st), int(en), int(sd))
        return a.at[tuple(sl)].set(v)
    return dispatch.call("slice_scatter", f, [_t(x), _t(value)])


def select_scatter(x, value, axis, index, name=None):
    """Write ``values`` into one index of ``axis`` (reference
    paddle.select_scatter)."""
    def f(a, v):
        sl = [builtins_slice(None)] * a.ndim
        sl[axis] = index
        return a.at[tuple(sl)].set(v)
    return dispatch.call("select_scatter", f, [_t(x), _t(value)])


@register("pad", category="manipulation")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """Pad by widths with constant/reflect/replicate/circular modes (reference
    paddle.nn.functional.pad)."""
    xt = _t(x)
    pad = _norm_shape(pad)
    nd = xt.ndim
    if len(pad) == 2 * nd:
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle semantics: pad applies to last len(pad)//2 spatial dims,
        # ordered innermost-first (like torch.nn.functional.pad)
        k = len(pad) // 2
        widths = [(0, 0)] * (nd - k)
        trailing = [(pad[2 * i], pad[2 * i + 1]) for i in range(k)]
        widths += list(reversed(trailing))
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return dispatch.call("pad", lambda a: jnp.pad(a, widths, mode="constant",
                                                      constant_values=value), [xt])
    return dispatch.call("pad", lambda a: jnp.pad(a, widths, mode=jmode), [xt])


def numel(x, name=None):
    """Scalar tensor holding the element count (reference paddle.numel)."""
    return Tensor(jnp.asarray(_t(x).size, dtype=jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Remap global ids to shard-local ids, ignore_value elsewhere (reference
    paddle.shard_index)."""
    def f(a):
        size = index_num // nshards
        shard = a // size
        local = a % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return dispatch.call("shard_index", f, [_t(input)])


def as_real(x, name=None):
    """View complex as trailing [real, imag] float pairs (reference
    paddle.as_real)."""
    def f(a):
        return jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1)
    return dispatch.call("as_real", f, [_t(x)])


def as_complex(x, name=None):
    """View trailing [real, imag] float pairs as complex (reference
    paddle.as_complex)."""
    return dispatch.call("as_complex",
                         lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [_t(x)])


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference phi unfold kernel)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    ks, st, pd, dl = _pair(kernel_sizes), _pair(strides), _pair(paddings), _pair(dilations)
    def f(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st,
            padding=[(pd[0], pd[0]), (pd[1], pd[1])],
            rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow] -> [N, C*kh*kw, oh*ow]
        return patches.reshape(n, patches.shape[1], -1)
    return dispatch.call("unfold", f, [_t(x)])


def tensordot(x, y, axes=2, name=None):
    """Generalized dot contracting the listed axes (reference
    paddle.tensordot)."""
    return dispatch.call("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes),
                         [_t(x), _t(y)])


def atleast_1d(*inputs, name=None):
    """Promote inputs to at least 1 dim (reference paddle.atleast_1d)."""
    outs = [dispatch.call("atleast_1d", jnp.atleast_1d, [_t(v)]) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    """Promote inputs to at least 2 dims (reference paddle.atleast_2d)."""
    outs = [dispatch.call("atleast_2d", jnp.atleast_2d, [_t(v)]) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    """Promote inputs to at least 3 dims (reference paddle.atleast_3d)."""
    outs = [dispatch.call("atleast_3d", jnp.atleast_3d, [_t(v)]) for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    """Extract a diagonal between two axes with offset (reference
    paddle.diagonal)."""
    return dispatch.call("diagonal",
                         lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
                         [_t(x)])


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    """Embed the last dim as diagonals of new trailing 2D planes (reference
    paddle.diag_embed)."""
    def f(a):
        out = jnp.zeros(a.shape + (a.shape[-1],), dtype=a.dtype)
        idx = jnp.arange(a.shape[-1])
        out = out.at[..., idx, idx].set(a)
        # move diag axes into requested positions
        return out
    return dispatch.call("diag_embed", f, [_t(x)])


def kron(x, y, name=None):
    """Kronecker product (reference paddle.kron)."""
    return dispatch.call("kron", jnp.kron, [_t(x), _t(y)])
