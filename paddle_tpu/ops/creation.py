"""Tensor creation ops (reference: python/paddle/tensor/creation.py,
random.py; phi full/arange/gaussian kernels). Random ops draw keys from the
stateful Generator facade (paddle_tpu.core.generator) so paddle.seed gives
reproducible streams on top of TPU counter-based PRNG.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtype import convert_dtype, float32, int64
from ..core.generator import next_key
from ..core.tensor import Tensor, as_tensor
from .registry import register

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like", "full_like",
    "empty", "empty_like", "arange", "linspace", "logspace", "eye", "diag", "diagflat",
    "tril", "triu", "meshgrid", "rand", "randn", "randint", "randint_like", "uniform",
    "normal", "standard_normal", "randperm", "multinomial", "bernoulli", "poisson",
    "exponential_", "tril_indices", "triu_indices", "one_hot", "clone", "assign",
    "complex", "polar",
]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """Construct a Tensor from python/numpy data with optional dtype (reference
    paddle.to_tensor)."""
    return as_tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def _shape(shape):
    if isinstance(shape, Tensor):
        # Tensor-valued shape -> python ints: creation ops need the
        # STATIC output shape XLA requires, so the read is the
        # host/graph boundary by design (same contract as
        # manipulation._norm_shape)
        return tuple(int(s) for s in np.asarray(shape._data))  # tpulint: disable=TPU103,TPU104 — static-shape construction from a shape tensor: host by design
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)


@register("zeros", category="creation", differentiable=False)
def zeros(shape, dtype=None, name=None):
    """All-zeros tensor of ``shape`` (reference paddle.zeros)."""
    return Tensor(jnp.zeros(_shape(shape), dtype=convert_dtype(dtype) or float32))


@register("ones", category="creation", differentiable=False)
def ones(shape, dtype=None, name=None):
    """All-ones tensor of ``shape`` (reference paddle.ones)."""
    return Tensor(jnp.ones(_shape(shape), dtype=convert_dtype(dtype) or float32))


@register("full", category="creation", differentiable=False)
def full(shape, fill_value, dtype=None, name=None):
    """Tensor of ``shape`` filled with ``fill_value`` (reference paddle.full).
    """
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    d = convert_dtype(dtype)
    if d is None:
        d = float32 if isinstance(fill_value, float) else None
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=d))


def zeros_like(x, dtype=None, name=None):
    """Zeros with the shape/dtype of ``x`` (reference paddle.zeros_like)."""
    return Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor) else x,
                                 dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    """Ones with the shape/dtype of ``x`` (reference paddle.ones_like)."""
    return Tensor(jnp.ones_like(x._data if isinstance(x, Tensor) else x,
                                dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    """``fill_value`` broadcast to the shape/dtype of ``x`` (reference
    paddle.full_like)."""
    return Tensor(jnp.full_like(x._data if isinstance(x, Tensor) else x, fill_value,
                                dtype=convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    """Uninitialized-contract tensor of ``shape`` (zero-filled on XLA)
    (reference paddle.empty)."""
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    """empty() with the shape/dtype of ``x`` (reference paddle.empty_like)."""
    return zeros_like(x, dtype)


@register("arange", category="creation", differentiable=False)
def arange(start=0, end=None, step=1, dtype=None, name=None):
    """Evenly spaced values in [start, end) with ``step`` (reference
    paddle.arange)."""
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        d = int64 if all(isinstance(v, (int, np.integer)) for v in (start, end, step)) else float32
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    """``num`` evenly spaced points in [start, stop] (reference
    paddle.linspace)."""
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=convert_dtype(dtype) or float32))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    """``num`` log-spaced points between base**start and base**stop (reference
    paddle.logspace)."""
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=convert_dtype(dtype) or float32))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    """Identity matrix, optionally rectangular (reference paddle.eye)."""
    return Tensor(jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype) or float32))


@register("diag", category="creation")
def diag(x, offset=0, padding_value=0, name=None):
    """Build a diagonal matrix from a vector, or extract a diagonal (reference
    paddle.diag)."""
    xt = as_tensor(x)
    def f(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k=offset, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, dtype=out.dtype))
            return out
        return jnp.diagonal(a, offset=offset)
    return dispatch.call("diag", f, [xt])


def diagflat(x, offset=0, name=None):
    """Flatten input then build a diagonal matrix (reference paddle.diagflat).
    """
    xt = as_tensor(x)
    return dispatch.call("diagflat", lambda a: jnp.diagflat(a, k=offset), [xt])


@register("tril", category="creation")
def tril(x, diagonal=0, name=None):
    """Lower-triangular part, zeroing above ``diagonal`` (reference
    paddle.tril)."""
    return dispatch.call("tril", lambda a: jnp.tril(a, k=diagonal), [as_tensor(x)])


@register("triu", category="creation")
def triu(x, diagonal=0, name=None):
    """Upper-triangular part, zeroing below ``diagonal`` (reference
    paddle.triu)."""
    return dispatch.call("triu", lambda a: jnp.triu(a, k=diagonal), [as_tensor(x)])


def meshgrid(*args, **kwargs):
    """Coordinate grids from 1D tensors, cartesian indexing (reference
    paddle.meshgrid)."""
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    ts = [as_tensor(a) for a in args]
    outs = dispatch.call("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), ts)
    return list(outs)


def tril_indices(row, col, offset=0, dtype="int64"):
    """Row/col indices of the lower triangle of an (m, n) grid (reference
    paddle.tril_indices)."""
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    """Row/col indices of the upper triangle of an (m, n) grid (reference
    paddle.triu_indices)."""
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=convert_dtype(dtype)))


# ------------------------------------------------------------------- random
@register("uniform", category="random", differentiable=False)
def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    """Sample U[min, max) of ``shape`` from the global generator (reference
    paddle.uniform)."""
    key = next_key() if seed == 0 else jax.random.key(seed)
    d = convert_dtype(dtype)
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=d, minval=min, maxval=max))


def rand(shape, dtype=None, name=None):
    """Sample U[0, 1) of ``shape`` (reference paddle.rand)."""
    return uniform(shape, dtype or "float32", 0.0, 1.0)


@register("gaussian", category="random", differentiable=False)
def normal(mean=0.0, std=1.0, shape=None, name=None):
    """Sample N(mean, std) of ``shape`` (reference paddle.normal; registered as
    gaussian too)."""
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean) if not isinstance(mean, Tensor) else mean
        s = as_tensor(std) if not isinstance(std, Tensor) else std
        shp = _shape(shape) if shape is not None else tuple(
            np.broadcast_shapes(tuple(m.shape), tuple(s.shape)))
        key = next_key()
        return dispatch.call(
            "gaussian", lambda mm, ss: mm + ss * jax.random.normal(key, shp, dtype=jnp.float32),
            [m, s])
    key = next_key()
    return Tensor(mean + std * jax.random.normal(key, _shape(shape or [1]), dtype=jnp.float32))


def randn(shape, dtype=None, name=None):
    """Sample N(0, 1) of ``shape`` (reference paddle.randn)."""
    key = next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype=convert_dtype(dtype) or float32))


def standard_normal(shape, dtype=None, name=None):
    """Sample N(0, 1) of ``shape`` (reference paddle.standard_normal)."""
    return randn(shape, dtype)


@register("randint", category="random", differentiable=False)
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    """Uniform random integers in [low, high) (reference paddle.randint)."""
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     dtype=convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    """randint with the shape of ``x`` (reference paddle.randint_like)."""
    xt = as_tensor(x)
    return randint(low, high, tuple(xt.shape), dtype or xt.dtype)


def randperm(n, dtype="int64", name=None):
    """Random permutation of [0, n) (reference paddle.randperm)."""
    return Tensor(jax.random.permutation(next_key(), n).astype(convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    """Sample category indices from unnormalized row weights (reference
    paddle.multinomial)."""
    xt = as_tensor(x)
    key = next_key()
    def f(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=(*p.shape[:-1], num_samples))
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, p.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    return dispatch.call("multinomial", f, [xt])


def bernoulli(x, name=None):
    """Sample {0,1} with per-element probability ``x`` (reference
    paddle.bernoulli)."""
    xt = as_tensor(x)
    key = next_key()
    return dispatch.call("bernoulli",
                         lambda p: jax.random.bernoulli(key, p).astype(p.dtype), [xt])


def poisson(x, name=None):
    """Sample Poisson with per-element rate ``x`` (reference paddle.poisson).
    """
    xt = as_tensor(x)
    key = next_key()
    return dispatch.call("poisson",
                         lambda lam: jax.random.poisson(key, lam).astype(lam.dtype), [xt])


def exponential_(x, lam=1.0, name=None):
    """In-place exponential(lam) resample of ``x`` (reference
    Tensor.exponential_)."""
    key = next_key()
    new = jax.random.exponential(key, tuple(x.shape), dtype=x._data.dtype) / lam
    x._swap_payload(new)
    return x


@register("one_hot", category="creation", differentiable=False)
def one_hot(x, num_classes, name=None):
    """Expand int labels to one-hot vectors of ``num_classes`` (reference
    paddle.nn.functional.one_hot)."""
    return dispatch.call("one_hot",
                         lambda a: jax.nn.one_hot(a, num_classes, dtype=jnp.float32),
                         [as_tensor(x)])


def clone(x, name=None):
    """Copy preserving autograd history (reference paddle.clone)."""
    return dispatch.call("clone", lambda a: a + 0, [as_tensor(x)])


def assign(x, output=None):
    """Copy input values into a (new or provided) tensor (reference
    paddle.assign)."""
    xt = as_tensor(x)
    out = dispatch.call("assign", lambda a: a + 0, [xt])
    if output is not None:
        output._swap_payload(out._data)
        return output
    return out


def complex(real, imag, name=None):
    """Build complex tensor from real and imaginary parts (reference
    paddle.complex)."""
    return dispatch.call("complex", jax.lax.complex, [as_tensor(real), as_tensor(imag)])


def polar(abs, angle, name=None):
    """abs * exp(i*angle) complex tensor (reference paddle.polar)."""
    return dispatch.call("polar",
                         lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                         [as_tensor(abs), as_tensor(angle)])
