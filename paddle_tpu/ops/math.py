"""Elementwise math, comparison, and logic ops.

Reference parity: paddle/phi/kernels elementwise + activation kernels and the
python surface python/paddle/tensor/math.py. TPU-native: each op is a jnp
lowering dispatched through paddle_tpu.core.dispatch (XLA fuses chains of
these into single HBM-friendly kernels; no per-op CUDA file needed).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor
from .registry import register

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _binary(name, jfn, x, y):
    xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
    if xt and yt:
        return dispatch.call(name, jfn, [x, y])
    if xt:
        return dispatch.call(name, lambda a: jfn(a, y), [x])
    if yt:
        return dispatch.call(name, lambda b: jfn(x, b), [y])
    return dispatch.call(name, jfn, [_t(x), _t(y)])


def _unary(name, jfn, x, **attrs):
    return dispatch.call(name, jfn, [_t(x)], attrs or None)


def _make_binary(name, jfn, aliases=()):
    def op(x, y, name_=None):
        return _binary(name, jfn, x, y)
    op.__name__ = name
    op.__qualname__ = name
    jdoc = getattr(jfn, "__name__", str(jfn))
    op.__doc__ = (f"Elementwise ``{name}(x, y)`` with numpy broadcasting "
                  f"(jnp.{jdoc} lowering, XLA-fused; reference "
                  f"paddle.{name}).")
    register(name, category="math")(op)
    _export(op)
    g = globals()
    g[name] = op
    for a in aliases:
        g[a] = op
        __all__.append(a)
    return op


def _make_unary(name, jfn, aliases=(), differentiable=True):
    def op(x, name_=None):
        return _unary(name, jfn, x)
    op.__name__ = name
    op.__qualname__ = name
    jdoc = getattr(jfn, "__name__", str(jfn))
    op.__doc__ = (f"Elementwise ``{name}(x)`` (jnp.{jdoc} lowering, "
                  f"XLA-fused; reference paddle.{name}).")
    register(name, category="math", differentiable=differentiable)(op)
    _export(op)
    g = globals()
    g[name] = op
    for a in aliases:
        g[a] = op
        __all__.append(a)
    return op


# -------------------------------------------------------------------- binary
_make_binary("add", jnp.add)
_make_binary("subtract", jnp.subtract)
_make_binary("multiply", jnp.multiply)
_make_binary("divide", jnp.true_divide)
_make_binary("floor_divide", jnp.floor_divide)
_make_binary("mod", jnp.mod, aliases=("remainder", "floor_mod"))
_make_binary("pow", jnp.power)
_make_binary("maximum", jnp.maximum)
_make_binary("minimum", jnp.minimum)
_make_binary("fmax", jnp.fmax)
_make_binary("fmin", jnp.fmin)
_make_binary("atan2", jnp.arctan2)
_make_binary("hypot", jnp.hypot)
_make_binary("logaddexp", jnp.logaddexp)
_make_binary("nextafter", jnp.nextafter)
_make_binary("copysign", jnp.copysign)
_make_binary("heaviside", jnp.heaviside)
_make_binary("gcd", jnp.gcd)
_make_binary("lcm", jnp.lcm)
_make_binary("ldexp", jnp.ldexp)

_make_binary("equal", jnp.equal)
_make_binary("not_equal", jnp.not_equal)
_make_binary("less_than", jnp.less, aliases=("less",))
_make_binary("less_equal", jnp.less_equal)
_make_binary("greater_than", jnp.greater, aliases=("greater",))
_make_binary("greater_equal", jnp.greater_equal)

_make_binary("logical_and", jnp.logical_and)
_make_binary("logical_or", jnp.logical_or)
_make_binary("logical_xor", jnp.logical_xor)
_make_binary("bitwise_and", jnp.bitwise_and)
_make_binary("bitwise_or", jnp.bitwise_or)
_make_binary("bitwise_xor", jnp.bitwise_xor)
_make_binary("bitwise_left_shift", jnp.left_shift)
_make_binary("bitwise_right_shift", jnp.right_shift)

# --------------------------------------------------------------------- unary
_make_unary("exp", jnp.exp)
_make_unary("expm1", jnp.expm1)
_make_unary("log", jnp.log)
_make_unary("log2", jnp.log2)
_make_unary("log10", jnp.log10)
_make_unary("log1p", jnp.log1p)
_make_unary("sqrt", jnp.sqrt)
_make_unary("rsqrt", lambda x: jax.lax.rsqrt(x))
_make_unary("square", jnp.square)
_make_unary("abs", jnp.abs)
_make_unary("neg", jnp.negative)
_make_unary("sign", jnp.sign)
_make_unary("floor", jnp.floor)
_make_unary("ceil", jnp.ceil)
_make_unary("round", jnp.round)
_make_unary("trunc", jnp.trunc)
_make_unary("frac", lambda x: x - jnp.trunc(x))
_make_unary("reciprocal", jnp.reciprocal)
_make_unary("sin", jnp.sin)
_make_unary("cos", jnp.cos)
_make_unary("tan", jnp.tan)
_make_unary("asin", jnp.arcsin)
_make_unary("acos", jnp.arccos)
_make_unary("atan", jnp.arctan)
_make_unary("sinh", jnp.sinh)
_make_unary("cosh", jnp.cosh)
_make_unary("tanh", jnp.tanh)
_make_unary("asinh", jnp.arcsinh)
_make_unary("acosh", jnp.arccosh)
_make_unary("atanh", jnp.arctanh)
_make_unary("erf", jax.scipy.special.erf)
_make_unary("erfinv", jax.scipy.special.erfinv)
_make_unary("sigmoid", jax.nn.sigmoid)
_make_unary("logit", jax.scipy.special.logit)
_make_unary("digamma", jax.scipy.special.digamma)
_make_unary("lgamma", jax.scipy.special.gammaln)
_make_unary("i0", lambda x: jax.scipy.special.i0(x))
_make_unary("i1", lambda x: jax.scipy.special.i1(x))
_make_unary("logical_not", jnp.logical_not, differentiable=False)
_make_unary("bitwise_not", jnp.bitwise_not, differentiable=False)
_make_unary("isnan", jnp.isnan, differentiable=False)
_make_unary("isinf", jnp.isinf, differentiable=False)
_make_unary("isfinite", jnp.isfinite, differentiable=False)
_make_unary("conj", jnp.conj)
_make_unary("angle", jnp.angle)
_make_unary("real", jnp.real)
_make_unary("imag", jnp.imag)


@register("scale", category="math")
@_export
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """paddle.scale (reference phi ScaleKernel)."""
    def f(a, scale, bias, bias_after_scale):
        out = a * scale + bias if bias_after_scale else (a + bias) * scale
        return out.astype(a.dtype) if np.issubdtype(np.dtype(a.dtype), np.integer) else out
    if isinstance(scale, Tensor):
        return dispatch.call("scale", lambda a, s: a * s + bias if bias_after_scale
                             else (a + bias) * s, [_t(x), scale])
    return dispatch.call("scale", f, [_t(x)],
                         dict(scale=scale, bias=bias, bias_after_scale=bias_after_scale))


@register("clip", category="math")
@_export
def clip(x, min=None, max=None, name=None):
    """Clamp to [min, max]; tensor bounds allowed (reference paddle.clip)."""
    if isinstance(min, Tensor) or isinstance(max, Tensor):
        mins = min if isinstance(min, Tensor) else _t(min if min is not None else -np.inf)
        maxs = max if isinstance(max, Tensor) else _t(max if max is not None else np.inf)
        return dispatch.call("clip", lambda a, lo, hi: jnp.clip(a, lo, hi), [_t(x), mins, maxs])
    return dispatch.call("clip", lambda a: jnp.clip(a, min, max), [_t(x)])


@register("lerp", category="math")
@_export
def lerp(x, y, weight, name=None):
    """x + weight * (y - x) (reference paddle.lerp)."""
    if isinstance(weight, Tensor):
        return dispatch.call("lerp", lambda a, b, w: a + w * (b - a), [_t(x), _t(y), weight])
    return dispatch.call("lerp", lambda a, b: a + weight * (b - a), [_t(x), _t(y)])


@register("stanh", category="math")
@_export
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """scale_b * tanh(scale_a * x) (reference paddle.stanh)."""
    return dispatch.call("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), [_t(x)])


@register("multiplex", category="math")
@_export
def multiplex(inputs, index, name=None):
    """Per-row select from a list of tensors by ``index`` (reference
    paddle.multiplex)."""
    ts = [_t(i) for i in inputs] + [_t(index)]
    def f(*args):
        *xs, idx = args
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))).astype(jnp.int32),
            axis=0)[0]
    return dispatch.call("multiplex", f, ts)


@register("isclose", category="math", differentiable=False)
@_export
def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    """Elementwise |x-y| <= atol + rtol*|y| with NaN handling (reference
    paddle.isclose)."""
    return dispatch.call("isclose",
                         lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                         [_t(x), _t(y)])


@register("allclose", category="math", differentiable=False)
@_export
def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    """Scalar: all elements isclose (reference paddle.allclose)."""
    return dispatch.call("allclose",
                         lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                         [_t(x), _t(y)])


@register("equal_all", category="math", differentiable=False)
@_export
def equal_all(x, y, name=None):
    """Scalar: exact elementwise equality of whole tensors (reference
    paddle.equal_all)."""
    return dispatch.call("equal_all", lambda a, b: jnp.array_equal(a, b), [_t(x), _t(y)])


@register("nan_to_num", category="math")
@_export
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    """Replace NaN/inf with finite substitutes (reference paddle.nan_to_num).
    """
    return dispatch.call("nan_to_num",
                         lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                         [_t(x)])


@register("trapezoid", category="math")
@_export
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal-rule integral along ``axis`` (reference paddle.trapezoid).
    """
    if x is not None:
        return dispatch.call("trapezoid",
                             lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis),
                             [_t(y), _t(x)])
    return dispatch.call("trapezoid",
                         lambda yy: jax.scipy.integrate.trapezoid(yy, dx=dx or 1.0, axis=axis),
                         [_t(y)])


@register("diff", category="math")
@_export
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    """n-th forward difference along ``axis`` with prepend/append (reference
    paddle.diff)."""
    ins = [_t(x)]
    def f(a, *rest):
        pre = rest[0] if prepend is not None else None
        app = rest[-1] if append is not None else None
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    if prepend is not None:
        ins.append(_t(prepend))
    if append is not None:
        ins.append(_t(append))
    return dispatch.call("diff", f, ins)


@register("cast", category="math")
@_export
def cast(x, dtype):
    """Convert to ``dtype``; vjp casts cotangents back (reference paddle.cast).
    """
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    xt = _t(x)
    if xt.dtype == d:
        return xt
    return dispatch.call("cast", lambda a: a.astype(d), [xt])


def gammaln(x, name=None):
    """lgamma alias (reference ops.yaml gammaln)."""
    import jax.scipy.special as jsp
    return dispatch.call("gammaln", jsp.gammaln, [_t(x)])


def polygamma(x, n, name=None):
    """n-th derivative of digamma (reference ops.yaml polygamma)."""
    import jax.scipy.special as jsp
    return dispatch.call("polygamma",
                         lambda a: jsp.polygamma(n, a), [_t(x)])


def i0e(x, name=None):
    """Exponentially scaled modified Bessel I0 (reference paddle.i0e)."""
    import jax.scipy.special as jsp
    return dispatch.call("i0e", jsp.i0e, [_t(x)])


def i1e(x, name=None):
    """Exponentially scaled modified Bessel I1 (reference paddle.i1e)."""
    import jax.scipy.special as jsp
    return dispatch.call("i1e", jsp.i1e, [_t(x)])


def increment(x, value=1.0, name=None):
    """In-place add of a scalar (reference ops.yaml increment)."""
    out = dispatch.call("increment", lambda a: a + value, [_t(x)])
    if isinstance(x, Tensor):
        x._swap_payload(out._data)
        return x
    return out


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along `axis` to at most max_norm in p-norm
    (reference ops.yaml renorm)."""
    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return dispatch.call("renorm", f, [_t(x)])


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Fill the (offset) diagonal (reference ops.yaml fill_diagonal):
    offset>0 above the main diagonal, offset<0 below; wrap=True restarts
    the diagonal after every (ncols+1) rows on tall matrices (numpy
    fill_diagonal semantics)."""
    def f(a):
        rows, cols = a.shape[-2], a.shape[-1]
        if wrap and offset == 0 and rows > cols:
            # wrapped main diagonal: rows i where i % (cols+1) < cols...
            # numpy semantics: flat stride cols+1 over the flattened matrix
            r = jnp.arange(rows)
            c = r % (cols + 1)
            ok = c < cols
            return a.at[..., r[ok], c[ok]].set(value)
        if offset >= 0:
            n = max(min(rows, cols - offset), 0)
            i = jnp.arange(n)
            return a.at[..., i, i + offset].set(value)
        n = max(min(rows + offset, cols), 0)
        i = jnp.arange(n)
        return a.at[..., i - offset, i].set(value)
    return dispatch.call("fill_diagonal", f, [_t(x)])


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (reference gammainc op,
    phi/kernels/impl/gammaincc_kernel_impl.h family)."""
    import jax.scipy.special as jsp
    return dispatch.call("gammainc", jsp.gammainc, [_t(x), _t(y)])


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y) (reference gammaincc op)."""
    import jax.scipy.special as jsp
    return dispatch.call("gammaincc", jsp.gammaincc, [_t(x), _t(y)])


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write tensor ``y`` onto the (dim1, dim2) diagonal band of ``x``
    (reference fill_diagonal_tensor op, phi/kernels/
    fill_diagonal_tensor_kernel.h). y's last axis runs along the diagonal."""
    x, y = _t(x), _t(y)

    def f(a, v):
        nd = a.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        # move the two diagonal dims to the back: (..., rows, cols)
        rest = [i for i in range(nd) if i not in (d1, d2)]
        perm = rest + [d1, d2]
        ap = jnp.transpose(a, perm)
        rows, cols = ap.shape[-2], ap.shape[-1]
        if offset >= 0:
            n = max(min(rows, cols - offset), 0)
            ri = jnp.arange(n)
            ci = ri + offset
        else:
            n = max(min(rows + offset, cols), 0)
            ri = jnp.arange(n) - offset
            ci = jnp.arange(n)
        ap = ap.at[..., ri, ci].set(v)
        inv = np.argsort(perm)
        return jnp.transpose(ap, inv)

    return dispatch.call("fill_diagonal_tensor", f, [x, y])


def reduce_as(x, target, name=None):
    """Sum-reduce ``x`` down to ``target``'s (broadcastable) shape
    (reference reduce_as op, phi/kernels/reduce_as_kernel.h)."""
    x, target = _t(x), _t(target)
    tshape = tuple(target.shape)

    def f(a):
        extra = a.ndim - len(tshape)
        axes = tuple(range(extra)) + tuple(
            i + extra for i, s in enumerate(tshape) if a.shape[i + extra] != s)
        out = jnp.sum(a, axis=axes, keepdims=False)
        return out.reshape(tshape)

    return dispatch.call("reduce_as", f, [x])


# i0 / i1 / logaddexp are factory-registered above (_make_unary/_make_binary)
__all__ += ["gammaln", "polygamma", "i0e", "i1e",
            "increment", "renorm", "fill_diagonal",
            "gammainc", "gammaincc", "fill_diagonal_tensor", "reduce_as"]
