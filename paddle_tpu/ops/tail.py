"""Top-level op-surface tail: the remaining reference ``paddle.*``
tensor functions.

Reference parity: python/paddle/tensor/{math,manipulation,attribute,
creation,random}.py entries present in the reference's top-level
``__all__`` but previously absent here. Each is a jnp lowering through
the standard dispatch pipeline (XLA fuses; autograd via lazy vjp).
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, as_tensor
from .registry import register

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _u(name, jfn, x):
    return dispatch.call(name, jfn, [_t(x)])


# ------------------------------------------------------------ elementwise
@_export
@register("rad2deg", category="math")
def rad2deg(x, name=None):
    """Radians to degrees (reference paddle.rad2deg)."""
    return _u("rad2deg", lambda a: a * (180.0 / _math.pi), x)


@_export
@register("deg2rad", category="math")
def deg2rad(x, name=None):
    """Degrees to radians (reference paddle.deg2rad)."""
    return _u("deg2rad", lambda a: a * (_math.pi / 180.0), x)


@_export
@register("sinc", category="math")
def sinc(x, name=None):
    """sin(pi x)/(pi x), 1 at 0 (reference paddle.sinc)."""
    return _u("sinc", jnp.sinc, x)


@_export
@register("sgn", category="math")
def sgn(x, name=None):
    """sign for real dtypes; x/|x| (0 at 0) for complex (reference sgn)."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / jnp.where(
                mag == 0, 1.0, mag))
        return jnp.sign(a)
    return _u("sgn", f, x)


@_export
@register("signbit", category="math", differentiable=False)
def signbit(x, name=None):
    """True where the sign bit is set, including -0.0 (reference
    paddle.signbit)."""
    return _u("signbit", jnp.signbit, x)


@_export
@register("frexp", category="math", differentiable=False)
def frexp(x, name=None):
    """Decompose into mantissa in [0.5, 1) and int exponent (reference
    paddle.frexp)."""
    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(a.dtype)
    return dispatch.call("frexp", f, [_t(x)])


@_export
@register("isneginf", category="math", differentiable=False)
def isneginf(x, name=None):
    """True at -inf entries (reference paddle.isneginf)."""
    return _u("isneginf", jnp.isneginf, x)


@_export
@register("isposinf", category="math", differentiable=False)
def isposinf(x, name=None):
    """True at +inf entries (reference paddle.isposinf)."""
    return _u("isposinf", jnp.isposinf, x)


@_export
@register("isreal", category="math", differentiable=False)
def isreal(x, name=None):
    """True where imaginary part is zero (reference paddle.isreal)."""
    return _u("isreal", jnp.isreal, x)


@_export
@register("multigammaln", category="math")
def multigammaln(x, p, name=None):
    """Log multivariate gamma of order p (reference paddle.multigammaln)."""
    from jax.scipy.special import multigammaln as _mg
    return _u("multigammaln", lambda a: _mg(a, int(p)), x)


# ------------------------------------------------------------- reductions
@_export
@register("cumulative_trapezoid", category="math")
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoid rule along ``axis`` (reference
    cumulative_trapezoid: output is one shorter along the axis)."""
    yt = _t(y)

    def f(ya, *rest):
        a = jnp.moveaxis(ya, axis, -1)
        mids = (a[..., 1:] + a[..., :-1]) * 0.5
        if rest:
            xa = jnp.moveaxis(rest[0], axis, -1)
            widths = jnp.diff(xa, axis=-1)
        else:
            widths = dx if dx is not None else 1.0
        out = jnp.cumsum(mids * widths, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    if x is not None:
        return dispatch.call("cumulative_trapezoid", f, [yt, _t(x)])
    return dispatch.call("cumulative_trapezoid", f, [yt])


@_export
@register("pdist", category="math")
def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of row vectors (reference pdist:
    upper-triangular part, row-major order)."""
    def f(a):
        n = a.shape[0]
        iu, ju = np.triu_indices(n, k=1)  # static: only real pairs —
        # no diagonal zeros whose sqrt'(0)=inf would NaN the vjp
        diff = a[jnp.asarray(iu)] - a[jnp.asarray(ju)]
        if p == 2.0:
            return jnp.sqrt((diff * diff).sum(-1))
        return (jnp.abs(diff) ** p).sum(-1) ** (1.0 / p)
    return _u("pdist", f, x)


@_export
@register("histogramdd", category="math", differentiable=False)
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """D-dimensional histogram (reference histogramdd) → (hist, edges).

    In-graph: ``jnp.histogramdd`` with integer ``bins`` has static
    output shapes, and a ``ranges=None`` data range resolves to the
    on-device min/max inside the program — no host readback, traceable
    under jit/to_static (the round-7 edit_distance rewrite pattern)."""
    xt = _t(x)
    ins = [xt]
    if weights is not None:
        ins.append(_t(weights))

    def f(a, *w):
        hist, edges = jnp.histogramdd(
            a, bins=bins, range=ranges, density=density,
            weights=(w[0] if w else None))
        return [hist.astype(jnp.float32)] + [e.astype(jnp.float32)
                                             for e in edges]

    out = dispatch.call("histogramdd", f, ins, multi_output=True,
                        differentiable_mask=[False] * len(ins))
    return out[0], list(out[1:])


# ----------------------------------------------------------- predicates
@_export
def is_complex(x):
    return bool(jnp.issubdtype(_t(x)._data.dtype, jnp.complexfloating))


@_export
def is_integer(x):
    return bool(jnp.issubdtype(_t(x)._data.dtype, jnp.integer))


@_export
def is_floating_point(x):
    return bool(jnp.issubdtype(_t(x)._data.dtype, jnp.floating))


@_export
def is_empty(x, name=None):
    """0-numel predicate, returned as a bool tensor (reference
    is_empty)."""
    return as_tensor(np.array(_t(x)._data.size == 0))


@_export
def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@_export
def tolist(x):
    return np.asarray(_t(x).numpy()).tolist()  # tpulint: disable=TPU101 — a python list IS the contract: tolist is the tensor protocol's host boundary, like Tensor.tolist (round-18 justification)


# ------------------------------------------------------------ structure
@_export
@register("block_diag", category="manipulation")
def block_diag(inputs, name=None):
    """Block-diagonal matrix from a list of matrices (reference
    paddle.block_diag)."""
    from jax.scipy.linalg import block_diag as _bd
    ts = [_t(i) for i in inputs]
    return dispatch.call("block_diag", lambda *a: _bd(*a), ts)


def _split_n(op_name, axis):
    def fn(x, num_or_indices, name=None):
        xt = _t(x)

        def f(a):
            ax = axis
            if op_name == "hsplit" and a.ndim == 1:
                ax = 0  # numpy/reference hsplit: 1-D splits axis 0
            if ax >= a.ndim:
                raise ValueError(f"{op_name} expects ndim > {ax}")
            if isinstance(num_or_indices, int):
                return tuple(jnp.split(a, num_or_indices, axis=ax))
            return tuple(jnp.split(a, list(num_or_indices), axis=ax))
        return dispatch.call(op_name, f, [xt])
    fn.__name__ = op_name
    fn.__doc__ = f"reference {op_name}: split along axis {axis}."
    return _export(register(op_name, category="manipulation")(fn))


hsplit = _split_n("hsplit", 1)
vsplit = _split_n("vsplit", 0)
dsplit = _split_n("dsplit", 2)


def _stack_as(op_name, jfn):
    def fn(x, name=None):
        ts = [_t(i) for i in x]
        return dispatch.call(op_name, lambda *a: jfn(a), ts)
    fn.__name__ = op_name
    fn.__doc__ = f"reference {op_name} (numpy-suite stacking)."
    return _export(register(op_name, category="manipulation")(fn))


hstack = _stack_as("hstack", jnp.hstack)
vstack = _stack_as("vstack", jnp.vstack)
dstack = _stack_as("dstack", jnp.dstack)
column_stack = _stack_as("column_stack", jnp.column_stack)
row_stack = _stack_as("row_stack", jnp.vstack)


@_export
@register("unflatten", category="manipulation")
def unflatten(x, axis, shape, name=None):
    """Split one dim into the given ``shape`` (reference paddle.unflatten)."""
    xt = _t(x)

    def f(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + [int(s) for s in shape] \
            + list(a.shape[ax + 1:])
        return jnp.reshape(a, new)
    return dispatch.call("unflatten", f, [xt])


@_export
@register("as_strided", category="manipulation", differentiable=False)
def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view as an explicit gather (XLA has no aliasing strides;
    reference as_strided over contiguous storage)."""
    xt = _t(x)

    def f(a):
        flat = a.reshape(-1)
        if not shape:
            return flat[offset]
        grids = jnp.meshgrid(
            *[jnp.arange(s) * st for s, st in zip(shape, stride)],
            indexing="ij")
        return flat[offset + sum(grids)]
    return dispatch.call("as_strided", f, [xt])


@_export
@register("index_fill", category="manipulation")
def index_fill(x, index, axis, value, name=None):
    """Set whole index positions along ``axis`` to ``value`` (reference
    paddle.index_fill)."""
    xt, it = _t(x), _t(index)

    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[idx].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return dispatch.call("index_fill", f, [xt, it],
                         differentiable_mask=[True, False])


@_export
@register("diagonal_scatter", category="manipulation")
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write values onto a diagonal of the input (reference
    paddle.diagonal_scatter)."""
    xt, yt = _t(x), _t(y)

    def f(a, b):
        n = min(a.shape[axis1], a.shape[axis2])
        moved = jnp.moveaxis(a, (axis1, axis2), (0, 1))
        k = b.shape[-1] if b.ndim else 1
        if offset >= 0:
            i = jnp.arange(k)
            j = i + offset
        else:
            j = jnp.arange(k)
            i = j - offset
        bb = jnp.moveaxis(jnp.atleast_1d(b), -1, 0) if b.ndim else b
        moved = moved.at[i, j].set(bb)
        return jnp.moveaxis(moved, (0, 1), (axis1, axis2))
    return dispatch.call("diagonal_scatter", f, [xt, yt])


@_export
@register("combinations", category="manipulation", differentiable=False)
def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor's elements (reference
    combinations)."""
    import itertools
    xt = _t(x)
    n = xt.shape[0]
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.array(list(it), np.int32).reshape(-1, r)

    def f(a):
        return a[jnp.asarray(idx)]
    return dispatch.call("combinations", f, [xt])


@_export
@register("scatter_nd", category="manipulation")
def scatter_nd(index, updates, shape, name=None):
    """Scatter ``updates`` into zeros of ``shape`` (reference
    scatter_nd = scatter_nd_add onto zeros)."""
    it, ut = _t(index), _t(updates)

    def f(idx, upd):
        out = jnp.zeros(tuple(int(s) for s in shape), upd.dtype)
        return out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return dispatch.call("scatter_nd", f, [it, ut],
                         differentiable_mask=[False, True])


@_export
@register("add_n", category="math")
def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (reference paddle.add_n)."""
    ts = [_t(i) for i in (inputs if isinstance(inputs, (list, tuple))
                          else [inputs])]
    return dispatch.call("add_n", lambda *a: sum(a[1:], a[0]), ts)


@_export
@register("reverse", category="manipulation")
def reverse(x, axis, name=None):
    """Legacy alias of flip (reference reverse → flip)."""
    from .manipulation import flip
    return flip(x, axis)


# --------------------------------------------------------------- random
@_export
@register("binomial", category="random", differentiable=False)
def binomial(count, prob, name=None):
    """Binomial(count, prob) draws (reference binomial)."""
    from ..core.generator import next_key
    ct, pt = _t(count), _t(prob)
    n = jnp.asarray(ct._data)
    p = jnp.asarray(pt._data)
    shape = jnp.broadcast_shapes(n.shape, p.shape)
    draws = jax.random.binomial(
        next_key(), n.astype(jnp.float32),
        p.astype(jnp.float32), shape=shape)
    return Tensor(draws.astype(jnp.int32))


@_export
@register("standard_gamma", category="random", differentiable=False)
def standard_gamma(x, name=None):
    """Gamma(alpha=x, scale=1) draws (reference standard_gamma)."""
    from ..core.generator import next_key
    xt = _t(x)
    return Tensor(jax.random.gamma(next_key(),
                                   jnp.asarray(xt._data,
                                               jnp.float32)).astype(
        xt._data.dtype if jnp.issubdtype(xt._data.dtype, jnp.floating)
        else jnp.float32))


@_export
@register("log_normal", category="random", differentiable=False)
def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """LogNormal(mean, std²) draws of ``shape`` (reference log_normal)."""
    from ..core.generator import next_key
    shape = tuple(shape or ())
    return Tensor(jnp.exp(
        jax.random.normal(next_key(), shape) * std + mean))
