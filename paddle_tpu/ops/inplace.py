"""In-place op variants (trailing-underscore API).

Reference: the ``x.op_()`` / ``paddle.op_(x)`` in-place family generated
alongside each op in the reference yaml (``paddle/phi/ops/yaml/ops.yaml``
``inplace:`` entries). TPU-native semantics: XLA buffers are immutable,
so "in-place" means the input tensor ADOPTS the result's buffer and grad
linkage (the idiom of ``reshape_``/``squeeze_``) — downstream autograd
continues from the op output exactly as the reference's inplace
var-rewrite does, with donation making it allocation-free under jit.

Random in-place fills (``normal_`` etc.) replace the payload with fresh
draws and sever the grad link (an initializer, not a differentiable op),
matching the reference's fill semantics.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, as_tensor
from .registry import OPS, register

#: in-place name -> base op name (base must be a registered op)
INPLACE_OF = {
    n + "_": n for n in """
    addmm cumsum cumprod logit equal cos tan logical_and less_than
    floor_divide remainder floor_mod logical_or bitwise_and bitwise_or
    bitwise_xor bitwise_not less_equal triu sin mod abs tril pow acos
    expm1 sinh neg lgamma gammaincc gammainc square divide gammaln atan
    gcd lcm cast greater_equal erf greater_than tanh transpose flatten
    multiply logical_not scatter log log2 log10 trunc frac digamma
    renorm nan_to_num index_add index_put ldexp i0 polygamma copysign
    bitwise_left_shift bitwise_right_shift masked_fill masked_scatter
    hypot sinc multigammaln index_fill""".split()
}
INPLACE_OF["t_"] = "t"

__all__ = sorted(INPLACE_OF) + [
    "normal_", "bernoulli_", "log_normal_", "cauchy_", "geometric_"]


def _adopt(x: Tensor, out: Tensor) -> Tensor:
    """x takes over out's buffer and autograd linkage."""
    x._swap_payload(out._data)
    x.grad_node = out.grad_node
    x.output_index = getattr(out, "output_index", 0)
    x.stop_gradient = out.stop_gradient
    return x


def _make_inplace(name: str, base_name: str):
    def fn(x, *args, **kwargs):
        # lazy lookup: some bases register after this module imports
        base = OPS[base_name].lowering
        out = base(x, *args, **kwargs)
        return _adopt(x, out)

    fn.__name__ = name
    fn.__doc__ = (f"In-place variant of ``{base_name}`` (payload swap + "
                  f"grad-link adoption; reference yaml inplace entry).")
    return register(name, category="inplace")(fn)


for _n, _b in INPLACE_OF.items():
    if _n not in OPS:
        globals()[_n] = _make_inplace(_n, _b)


# ------------------------------------------------------- random fills
def _fill(x, sample) -> Tensor:
    x = as_tensor(x)
    x._swap_payload(sample.astype(x._data.dtype))
    x.grad_node = None  # an initializer: the fill severs the tape
    return x


def _key():
    from ..core.generator import next_key
    return next_key()


@register("normal_", category="inplace", differentiable=False)
def normal_(x, mean=0.0, std=1.0, name=None):
    """Fill ``x`` with N(mean, std²) draws (reference normal_)."""
    import jax
    x = as_tensor(x)
    return _fill(x, jax.random.normal(_key(), x._data.shape) * std + mean)


@register("bernoulli_", category="inplace", differentiable=False)
def bernoulli_(x, p=0.5, name=None):
    """Fill with Bernoulli(p) zeros/ones (reference bernoulli_)."""
    import jax
    x = as_tensor(x)
    return _fill(x, jax.random.bernoulli(
        _key(), p, x._data.shape).astype(jnp.float32))


@register("log_normal_", category="inplace", differentiable=False)
def log_normal_(x, mean=1.0, std=2.0, name=None):
    """Fill with LogNormal(mean, std²): exp of a normal draw."""
    import jax
    x = as_tensor(x)
    return _fill(x, jnp.exp(
        jax.random.normal(_key(), x._data.shape) * std + mean))


@register("cauchy_", category="inplace", differentiable=False)
def cauchy_(x, loc=0.0, scale=1.0, name=None):
    """Fill with Cauchy(loc, scale) draws (reference cauchy_)."""
    import jax
    x = as_tensor(x)
    return _fill(x, jax.random.cauchy(
        _key(), x._data.shape) * scale + loc)


@register("geometric_", category="inplace", differentiable=False)
def geometric_(x, probs, name=None):
    """Fill with Geometric(probs) draws (trial count of first success,
    starting at 1 — reference geometric_)."""
    import jax
    x = as_tensor(x)
    u = jax.random.uniform(
        _key(), x._data.shape, minval=jnp.finfo(jnp.float32).tiny)
    return _fill(x, jnp.ceil(jnp.log(u) / jnp.log1p(-probs)))
