"""Pallas TPU kernels for the hot ops.

TPU-native replacement for the reference's hand-written fused CUDA kernels
(reference: paddle/phi/kernels/fusion/gpu/ and third_party/flashattn). Only
the truly bandwidth/latency-critical ops get kernels here — everything else
is left to XLA fusion. ``serving`` holds the serving tier's in-graph
helpers (int8 KV page (de)quant, the speculative-decode accept-prefix
step) that the paged-attention op and engine verify program compose.
"""
