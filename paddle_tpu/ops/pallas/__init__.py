"""Pallas TPU kernels for the hot ops.

TPU-native replacement for the reference's hand-written fused CUDA kernels
(reference: paddle/phi/kernels/fusion/gpu/ and third_party/flashattn). Only
the truly bandwidth/latency-critical ops get kernels here — everything else
is left to XLA fusion.
"""
