"""Fused Pallas TPU kernels behind the graph-fusion pass.

Siblings to :mod:`.flash_attention`, covering the reference's hot fused
kernels (reference: paddle/phi/kernels/fusion/ — fused_layernorm,
fused_bias_act, fused_rope; 71 entries in fused_ops.yaml). Each kernel
is the *measured* alternative the per-shape autotuner
(:mod:`.autotune`) weighs against the XLA-fused jnp composite — the
composite is always the numerics reference and the portable fallback.

Kernels:

* ``fused_residual_norm`` — residual add + LayerNorm / RMSNorm over the
  last dim in one pass, emitting both the normalized value AND the sum,
  so the residual stream never round-trips HBM between the add and the
  norm.
* ``fused_matmul`` — ``act(norm(x) @ W + b)``: a row-panel matmul whose
  prologue normalizes the activation rows in-register (full K resident
  per tile) and whose epilogue applies bias + GELU/SiLU/ReLU before the
  single output store. One HBM round-trip where the unfused chain makes
  three or four.
* ``fused_matmul_rope`` — QKV-style projection with the rotary
  embedding applied in the epilogue: out tiles are rotated per head
  before the store (positions recovered from the row index), so the
  projected tensor lands in HBM already roped.

All kernels run under the Pallas interpreter (``INTERPRET = True``) so
CPU tests execute the real kernel bodies. Shape gates (`pallas_ok_*`)
keep the kernels on aligned shapes — anything else takes the composite.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: run kernels through the Pallas interpreter (CPU testing of kernel code)
INTERPRET = False

# Tile candidates for the measured autotuner (ops/pallas/autotune.py) —
# small grids on purpose: each candidate costs one Mosaic compile at
# first sight of a (shape-class, chip) key; winners persist to disk.
NORM_ROW_CANDIDATES = [256, 512, 1024]
MATMUL_TILE_CANDIDATES = [(256, 256), (512, 256), (256, 512), (128, 512),
                          (512, 512)]

DEFAULT_NORM_ROWS = 512
DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256

#: VMEM budget the matmul tiles must fit (x panel + w panel + acc, f32)
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _act_apply(y, act: str):
    """Epilogue activation on the fp32 accumulator (closed vocabulary —
    the fusion pass only rewrites activations listed here). The ONE
    implementation: nn.functional.fused's composites delegate here, so
    kernel and numerics reference share the same vocabulary; the public
    name list is nn.functional.fused.ACTIVATIONS."""
    if act == "gelu":
        return jax.nn.gelu(y, approximate=False)
    if act == "gelu_tanh":
        return jax.nn.gelu(y, approximate=True)
    if act == "silu":
        return jax.nn.silu(y)
    if act == "relu":
        return jnp.maximum(y, 0.0)
    if act in ("", "none", None):
        return y
    raise ValueError(f"unknown fused activation {act!r}")


def _normalize_rows(x32, w32, b32, kind: str, eps: float):
    """Row-wise LN/RMS in fp32: x32 (R, D), w32/b32 (1, D)."""
    if kind == "rms_norm":
        ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(ms + eps)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        centered = x32 - mean
        var = jnp.mean(centered * centered, axis=-1, keepdims=True)
        y = centered * jax.lax.rsqrt(var + eps)
    return y * w32 + b32


# --------------------------------------------------------------------------
# fused (residual+)norm
# --------------------------------------------------------------------------
def _norm_kernel(x_ref, res_ref, w_ref, b_ref, y_ref, sum_ref, *, kind,
                 eps):
    x32 = (x_ref[...].astype(jnp.float32)
           + res_ref[...].astype(jnp.float32))
    sum_ref[...] = x32.astype(sum_ref.dtype)
    w32 = w_ref[...].astype(jnp.float32)
    b32 = b_ref[...].astype(jnp.float32)
    y_ref[...] = _normalize_rows(x32, w32, b32, kind, eps).astype(
        y_ref.dtype)


def pallas_ok_norm(rows: int, d: int) -> bool:
    """Aligned shapes only: the norm statistics are exact only when the
    feature dim is fully resident (no padding lanes)."""
    return d % 128 == 0 and rows >= 8 and d * 8 * 4 <= _VMEM_BUDGET_BYTES


def _pad_rows(x, block_r):
    pad = (-x.shape[0]) % block_r
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x


def fused_residual_norm(x2d, res2d, weight, bias, *, kind="layer_norm",
                        eps=1e-5, block_rows=None):
    """One pass: ``s = x + res; y = norm(s) * w + b`` → ``(y, s)``."""
    r, d = x2d.shape
    block_rows = int(block_rows or DEFAULT_NORM_ROWS)
    block_rows = max(8, min(block_rows, max(r, 8)))
    xp = _pad_rows(x2d, block_rows)
    sp = _pad_rows(res2d, block_rows)
    rp = xp.shape[0]
    w2 = weight.reshape(1, d)
    b2 = bias.reshape(1, d)
    kernel = functools.partial(_norm_kernel, kind=kind, eps=eps)
    y, s = pl.pallas_call(
        lambda x_ref, res_ref, w_ref, b_ref, y_ref, sum_ref: kernel(
            x_ref, res_ref, w_ref, b_ref, y_ref, sum_ref),
        out_shape=[jax.ShapeDtypeStruct((rp, d), x2d.dtype),
                   jax.ShapeDtypeStruct((rp, d), x2d.dtype)],
        grid=(rp // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
                   pl.BlockSpec((block_rows, d), lambda i: (i, 0))],
        interpret=INTERPRET,
    )(xp, sp, w2, b2)
    return y[:r], s[:r]


# --------------------------------------------------------------------------
# fused bias+act (elementwise epilogue as its own kernel, for graphs whose
# matmul is out of pallas reach — e.g. parallel layers adding bias
# separately after a sharded matmul)
# --------------------------------------------------------------------------
def _bias_act_kernel(x_ref, b_ref, y_ref, *, act):
    y = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = _act_apply(y, act).astype(y_ref.dtype)


def fused_bias_act(x2d, bias, *, act="gelu", block_rows=None):
    """``act(x + b)`` over (R, D) with b (D,), one VPU pass."""
    r, d = x2d.shape
    block_rows = int(block_rows or DEFAULT_NORM_ROWS)
    block_rows = max(8, min(block_rows, max(r, 8)))
    xp = _pad_rows(x2d, block_rows)
    rp = xp.shape[0]
    return pl.pallas_call(
        functools.partial(_bias_act_kernel, act=act),
        out_shape=jax.ShapeDtypeStruct((rp, d), x2d.dtype),
        grid=(rp // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=INTERPRET,
    )(xp, bias.reshape(1, d))[:r]


# --------------------------------------------------------------------------
# fused (norm→)matmul(→bias→act)
# --------------------------------------------------------------------------
def _matmul_kernel(x_ref, w_ref, b_ref, nw_ref, nb_ref, o_ref, *,
                   norm_kind, act, eps):
    x32 = x_ref[...].astype(jnp.float32)          # (bm, K)
    if norm_kind:
        x32 = _normalize_rows(x32, nw_ref[...].astype(jnp.float32),
                              nb_ref[...].astype(jnp.float32),
                              norm_kind, eps)
    # MXU consumes the input dtype (bf16 stays bf16); accumulate fp32
    acc = jax.lax.dot_general(
        x32.astype(x_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # (bm, bn)
    acc = acc + b_ref[...].astype(jnp.float32)
    o_ref[...] = _act_apply(acc, act).astype(o_ref.dtype)


def pallas_ok_matmul(m: int, k: int, n: int, block_m: int,
                     block_n: int) -> bool:
    """The row-panel kernel keeps full K resident per tile: gate on lane
    alignment and the VMEM footprint of (x panel + w panel + acc)."""
    if k % 128 != 0 or n % block_n != 0:
        return False
    need = 4 * (block_m * k + k * block_n + block_m * block_n)
    return need <= _VMEM_BUDGET_BYTES


def fused_matmul(x2d, w, bias=None, norm_weight=None, norm_bias=None, *,
                 norm_kind="", act="", eps=1e-5, block_m=None,
                 block_n=None):
    """``act(norm(x) @ W + b)`` over x (M, K), W (K, N) in one kernel."""
    m, k = x2d.shape
    n = w.shape[1]
    block_m = int(block_m or DEFAULT_BLOCK_M)
    block_n = int(block_n or DEFAULT_BLOCK_N)
    block_m = max(8, min(block_m, max(m, 8)))
    block_n = min(block_n, n)
    xp = _pad_rows(x2d, block_m)
    mp = xp.shape[0]
    b2 = (bias if bias is not None
          else jnp.zeros((n,), x2d.dtype)).reshape(1, n)
    nw2 = (norm_weight if norm_weight is not None
           else jnp.ones((k,), x2d.dtype)).reshape(1, k)
    nb2 = (norm_bias if norm_bias is not None
           else jnp.zeros((k,), x2d.dtype)).reshape(1, k)
    out = pl.pallas_call(
        functools.partial(_matmul_kernel, norm_kind=norm_kind, act=act,
                          eps=eps),
        out_shape=jax.ShapeDtypeStruct((mp, n), x2d.dtype),
        grid=(mp // block_m, n // block_n),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, k), lambda i, j: (0, 0)),
            pl.BlockSpec((1, k), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        interpret=INTERPRET,
    )(xp, w, b2, nw2, nb2)
    return out[:m]


# --------------------------------------------------------------------------
# fused matmul → rope epilogue (QKV projection that lands already-roped)
# --------------------------------------------------------------------------
def _matmul_rope_kernel(x_ref, w_ref, b_ref, o_ref, *, seq, head_dim,
                        theta, pos_offset, block_m, block_n):
    i = pl.program_id(0)
    x = x_ref[...]                                 # (bm, K)
    acc = jax.lax.dot_general(
        x, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # (bm, bn)
    acc = acc + b_ref[...].astype(jnp.float32)
    # rows are the flattened (batch, seq) axis: position = row % seq
    half = head_dim // 2
    rows = i * block_m + jax.lax.broadcasted_iota(
        jnp.int32, (block_m, 1), 0)
    pos = (rows % seq).astype(jnp.float32) + float(pos_offset)
    freqs = 1.0 / (theta ** (jax.lax.broadcasted_iota(
        jnp.float32, (1, half), 1) / half))
    angle = pos * freqs                            # (bm, half)
    cos = jnp.cos(angle)[:, None, :]               # (bm, 1, half)
    sin = jnp.sin(angle)[:, None, :]
    heads_per_tile = block_n // head_dim
    a = acc.reshape(block_m, heads_per_tile, head_dim)
    x1, x2 = a[..., :half], a[..., half:]
    roped = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    o_ref[...] = roped.reshape(block_m, block_n).astype(o_ref.dtype)


def pallas_ok_matmul_rope(m: int, k: int, n: int, head_dim: int,
                          block_m: int, block_n: int) -> bool:
    """Rope rotation pairs channels within one head: each out tile must
    cover whole heads, and the head dim must split into even halves."""
    return (pallas_ok_matmul(m, k, n, block_m, block_n)
            and head_dim % 2 == 0 and block_n % head_dim == 0)


def fused_matmul_rope(x2d, w, bias=None, *, seq, head_dim,
                      theta=10000.0, pos_offset=0, block_m=None,
                      block_n=None):
    """``rope(reshape(x @ W + b))`` over x (B*S, K): the epilogue
    rotates each head's channel pairs before the single store."""
    m, k = x2d.shape
    n = w.shape[1]
    block_m = int(block_m or DEFAULT_BLOCK_M)
    block_n = int(block_n or DEFAULT_BLOCK_N)
    block_m = max(8, min(block_m, max(m, 8)))
    block_n = min(block_n, n)
    if block_n % head_dim:
        block_n = (block_n // head_dim or 1) * head_dim
    # positions are recovered as row % seq — padded rows would alias
    # position 0..pad, which is harmless (their outputs are sliced off)
    xp = _pad_rows(x2d, block_m)
    mp = xp.shape[0]
    b2 = (bias if bias is not None
          else jnp.zeros((n,), x2d.dtype)).reshape(1, n)
    out = pl.pallas_call(
        functools.partial(_matmul_rope_kernel, seq=int(seq),
                          head_dim=int(head_dim), theta=float(theta),
                          pos_offset=int(pos_offset), block_m=block_m,
                          block_n=block_n),
        out_shape=jax.ShapeDtypeStruct((mp, n), x2d.dtype),
        grid=(mp // block_m, n // block_n),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, block_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
        interpret=INTERPRET,
    )(xp, w, b2)
    return out[:m]
