"""Flash attention (forward + backward) as Pallas TPU kernels.

Replaces the reference's FlashAttention-2 CUDA library integration
(reference: third_party/flashattn; op `flash_attn` at
paddle/phi/ops/yaml/ops.yaml:1635). Design:

* forward — online-softmax over KV tiles: grid (batch*heads, q_tiles,
  kv_tiles) with the kv axis innermost so the fp32 accumulators in VMEM
  scratch persist across kv steps; the MXU consumes (Bq, d) x (d, Bk)
  tiles; causal tiles above the diagonal are skipped with @pl.when so no
  FLOPs are spent on masked blocks. Also emits the per-row logsumexp
  (the FA2 "L" residual) for backward.
* backward — the FA2 recompute strategy, O(S·d) memory: residuals are only
  (q, k, v, out, lse); each backward tile recomputes p = exp(qk·scale−lse)
  on the fly. Two kernels: dQ iterates kv innermost accumulating
  dq += ds·K; dK/dV iterates q innermost accumulating dv += pᵀ·dO and
  dk += dsᵀ·Q, where ds = p·(dp − Δ)·scale, dp = dO·Vᵀ and
  Δ = rowsum(dO∘O) is precomputed by one fused XLA reduction. The full
  (S, S) probability matrix is never materialized in either pass.

``block_q`` / ``block_k`` are exposed for tuning (reference
flash_attn's num_splits analog); ``INTERPRET=True`` runs the same kernels
through the Pallas interpreter so CPU tests cover the real kernel code.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Tuning knobs (VMEM-footprint vs pipeline depth); override per call via
# flash_attention_fwd(..., block_q=..., block_k=...).
DEFAULT_BLOCK_Q = 1024      # tuned on v5e @ S=8k: 23 TF/s vs 19 at 512
DEFAULT_BLOCK_K = 1024
DEFAULT_BWD_BLOCK_Q = 512
DEFAULT_BWD_BLOCK_K = 512


def _bwd_block_for(seq):
    """Backward tile size for ONE side (q or k), from that side's length:
    1024 wins at short/medium seq (measured on v5e: 82.0ms vs 84.1ms GPT-2
    step @ S=1024) but only when it divides the seq (otherwise padding
    wastes up to 33% of the grid); longer seqs keep the 512 tiles that hold
    the dKdV accumulators in VMEM (the original 8k tuning)."""
    if seq <= 2048 and seq % 1024 == 0:
        return 1024
    return DEFAULT_BWD_BLOCK_Q

#: run kernels in the Pallas interpreter (CPU testing of kernel code)
INTERPRET = False

# Candidate tile grids for the measured autotuner (ops/pallas/autotune.py).
# Small on purpose: each candidate costs one Pallas compile at first sight
# of a new (shape-class, chip) key; winners persist to disk.
FWD_TILE_CANDIDATES = [(1024, 1024), (512, 512), (512, 1024), (1024, 512),
                       (2048, 512)]
BWD_TILE_CANDIDATES = [(512, 512), (1024, 1024), (256, 512), (512, 1024),
                       (1024, 512)]


def _tuned_blocks(kind, bh, s_q, s_k, d, dtype, causal, scale):
    """Measured (block_q, block_k) for this shape class on this chip.

    Falls back to the hand-tuned v5e constants when autotuning is off or
    the backend is not a real TPU (reference
    phi/kernels/autotune/switch_autotune.cc gate). Benchmarks run on
    zeros at the BUCKETED sequence lengths (tile ranking is data- and
    batch-mostly-independent; batch*heads is capped at 8 to keep the
    probe cheap) — safe to call at trace time, since the probe inputs
    are concrete.
    """
    from . import autotune as at

    if INTERPRET or not at.should_autotune():
        if kind == "fwd":
            return DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
        return _bwd_block_for(s_q), _bwd_block_for(s_k)

    sq_b, sk_b = at.seq_bucket(s_q), at.seq_bucket(s_k)
    key = at.make_key(f"flash_{kind}", sq=sq_b, sk=sk_b, d=d,
                      dt=str(jnp.dtype(dtype)), causal=bool(causal))
    cached = at.get_cache().get(key)
    if cached is not None:
        return tuple(cached)

    bh_b = min(bh, 8)
    # probe on noise, not zeros (constant-folding could skip real work),
    # with several DISTINCT inputs cycled across timed iterations
    # (replay-caching backends fake repeat-identical executions)
    nvar = 3
    qs, ks, vs = [], [], []
    for i in range(nvar):
        kp = jax.random.key(i)
        qs.append(jax.random.normal(kp, (bh_b, sq_b, d)).astype(dtype))
        ks.append(jax.random.normal(
            jax.random.fold_in(kp, 1), (bh_b, sk_b, d)).astype(dtype))
        vs.append(jax.random.normal(
            jax.random.fold_in(kp, 2), (bh_b, sk_b, d)).astype(dtype))
    # amortize per-call dispatch/transport under the kernel: chain K
    # applications data-dependently inside ONE program (the kernel's
    # q-shaped output feeds the next iteration), sized so device time
    # dominates even a ~100 ms remote-dispatch floor
    kernel_flops = 4.0 * bh_b * sq_b * sk_b * d * (0.5 if causal else 1.0)
    reps = at.probe_reps(kernel_flops)
    jitted = {}
    if kind == "fwd":
        candidates, default = FWD_TILE_CANDIDATES, (DEFAULT_BLOCK_Q,
                                                    DEFAULT_BLOCK_K)

        def run(c, i):
            fn = jitted.get(c)
            if fn is None:
                kern = functools.partial(
                    _flash_fwd_bhsd, causal=causal, scale=scale,
                    block_q=c[0], block_k=c[1])

                def chained(q0, k0, v0):
                    return jax.lax.fori_loop(
                        0, reps, lambda _, q: kern(q, k0, v0)[0], q0)

                fn = jitted[c] = jax.jit(chained)
            j = i % nvar
            return fn(qs[j], ks[j], vs[j])
    else:
        candidates = BWD_TILE_CANDIDATES
        default = (_bwd_block_for(s_q), _bwd_block_for(s_k))
        fwd = jax.jit(functools.partial(
            _flash_fwd_bhsd, causal=causal, scale=scale,
            block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K))
        outs, lses = zip(*(fwd(qs[j], ks[j], vs[j])
                           for j in range(nvar)))

        def run(c, i):
            fn = jitted.get(c)
            if fn is None:
                kern = functools.partial(
                    _flash_bwd_bhsd, causal=causal, scale=scale,
                    block_q=c[0], block_k=c[1])

                def chained(q0, k0, v0, o0, l0, g0):
                    return jax.lax.fori_loop(
                        0, reps,
                        lambda _, q: kern(q, k0, v0, o0, l0, g0)[0], q0)

                fn = jitted[c] = jax.jit(chained)
            j = i % nvar
            return fn(qs[j], ks[j], vs[j], outs[j], lses[j], outs[j])

    return tuple(at.autotune(key, candidates, run, default,
                             warmup=2, iters=5))


def _causal_run(q_idx, kv_idx, block_q, block_k, offset):
    """Tile intersects the bottom-right-aligned causal region."""
    return kv_idx * block_k <= q_idx * block_q + (block_q - 1) + offset


def _tile_mask(q_idx, kv_idx, block_q, block_k, seq_k, causal, offset):
    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = k_pos < seq_k
    if causal:
        mask = mask & (q_pos + offset >= k_pos)
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, seq_q, seq_k):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)
    # Bottom-right-aligned causal diagonal (matches tril(..., k=t-s) in the
    # XLA reference path): query i attends keys <= i + (seq_k - seq_q).
    causal_offset = seq_k - seq_q

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        run = _causal_run(q_idx, kv_idx, block_q, block_k, causal_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]          # (block_q, d)
        k = k_ref[0]          # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(q_idx, kv_idx, block_q, block_k, seq_k, causal,
                          causal_offset)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]                      # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                 # (block_q, block_k)
        # fully-masked rows (causal, seq_q > seq_k): m_new == NEG_INF and
        # exp(s - m_new) == 1; zero them so l stays 0 and out stays 0
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kv_idx == num_kv - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_k, seq_q, seq_k):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)
    causal_offset = seq_k - seq_q

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = _causal_run(q_idx, kv_idx, block_q, block_k, causal_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                       # (block_q, 1)
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(q_idx, kv_idx, block_q, block_k, seq_k, causal,
                          causal_offset)
        s = jnp.where(mask, s, NEG_INF)
        # mask-guard (not just exp underflow): for fully-masked rows lse is
        # garbage (~NEG_INF) and exp(NEG_INF - lse) would be 1, not 0
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale          # (block_q, block_k) fp32
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == num_kv - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_scr, dv_scr, *, scale, causal, block_q, block_k,
                seq_q, seq_k):
    q_idx = pl.program_id(2)       # q innermost in this kernel
    kv_idx = pl.program_id(1)
    num_q = pl.num_programs(2)
    causal_offset = seq_k - seq_q

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = _causal_run(q_idx, kv_idx, block_q, block_k, causal_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _tile_mask(q_idx, kv_idx, block_q, block_k, seq_k, causal,
                          causal_offset)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        # dv += P^T dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dk += dS^T Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_idx == num_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pad_bhsd(x, block_s, pad_d):
    pad_s = (-x.shape[1]) % block_s
    if pad_s or pad_d:
        x = jnp.pad(x, ((0, 0), (0, pad_s), (0, pad_d)))
    return x


def _flash_fwd_bhsd(q, k, v, *, causal, scale, block_q, block_k):
    """q/k/v: (BH, S, d) -> (out (BH, S, d), lse fp32 (BH, Sq_padded))."""
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, max(s_q, 8))
    block_k = min(block_k, max(s_k, 8))
    pad_d = (-d) % 128
    q = _pad_bhsd(q, block_q, pad_d)
    k = _pad_bhsd(k, block_k, pad_d)
    v = _pad_bhsd(v, block_k, pad_d)
    sp_q, sp_k, dp = q.shape[1], k.shape[1], d + pad_d

    grid = (bh, sp_q // block_q, sp_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_q=s_q, seq_k=s_k)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((bh, sp_q, dp), q.dtype),
                   jax.ShapeDtypeStruct((bh, sp_q, 1), jnp.float32)],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ],
        interpret=INTERPRET,
    )(q, k, v)
    return out[:, :s_q, :d], lse


def _flash_bwd_bhsd(q, k, v, out, lse, do, *, causal, scale, block_q,
                    block_k):
    """FA2 backward. All of q/k/v/out/do: (BH, S, d); lse: (BH, Sq_pad_fwd).
    Returns (dq, dk, dv) unpadded."""
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, max(s_q, 8))
    block_k = min(block_k, max(s_k, 8))
    pad_d = (-d) % 128

    # Δ = rowsum(dO ∘ O): one fused XLA reduction, fp32.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                # (BH, s_q, 1)

    q = _pad_bhsd(q, block_q, pad_d)
    do = _pad_bhsd(do, block_q, pad_d)
    k = _pad_bhsd(k, block_k, pad_d)
    v = _pad_bhsd(v, block_k, pad_d)
    sp_q, sp_k, dp = q.shape[1], k.shape[1], d + pad_d
    if lse.shape[1] < sp_q:     # fwd may have tiled with a different block
        lse = jnp.pad(lse, ((0, 0), (0, sp_q - lse.shape[1]), (0, 0)))
    elif lse.shape[1] > sp_q:
        lse = lse[:, :sp_q]
    delta = jnp.pad(delta, ((0, 0), (0, sp_q - s_q), (0, 0)))

    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              seq_q=s_q, seq_k=s_k)
    q_spec = pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **kw),
        out_shape=jax.ShapeDtypeStruct((bh, sp_q, dp), q.dtype),
        grid=(bh, sp_q // block_q, sp_k // block_k),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
            q_spec, row_spec, row_spec,
        ],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        interpret=INTERPRET,
    )(q, k, v, do, lse, delta)

    # dk/dv: kv outer, q inner
    qi_spec = pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, j, 0))
    rowi_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    kv_spec = pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **kw),
        out_shape=[jax.ShapeDtypeStruct((bh, sp_k, dp), k.dtype),
                   jax.ShapeDtypeStruct((bh, sp_k, dp), v.dtype)],
        grid=(bh, sp_k // block_k, sp_q // block_q),
        in_specs=[qi_spec, kv_spec, kv_spec, qi_spec, rowi_spec, rowi_spec],
        out_specs=[kv_spec, kv_spec],
        scratch_shapes=[pltpu.VMEM((block_k, dp), jnp.float32),
                        pltpu.VMEM((block_k, dp), jnp.float32)],
        interpret=INTERPRET,
    )(q, k, v, do, lse, delta)
    return (dq[:, :s_q, :d], dk[:, :s_k, :d], dv[:, :s_k, :d])


def _bshd_to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _bhsd_to_bshd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    b, s, h, d = q.shape
    if block_q is None or block_k is None:
        tq, tk = _tuned_blocks("fwd", b * h, s, k.shape[1], d, q.dtype,
                               causal, scale)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    out, lse = _flash_fwd_bhsd(
        _bshd_to_bhsd(q), _bshd_to_bhsd(k), _bshd_to_bhsd(v),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k)
    out_bshd = _bhsd_to_bshd(out, b, h)
    return out_bshd, (q, k, v, out_bshd, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    s_k = k.shape[1]
    if block_q is None or block_k is None:
        tq, tk = _tuned_blocks("bwd", b * h, s, s_k, d, q.dtype, causal,
                               scale)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    dq, dk, dv = _flash_bwd_bhsd(
        _bshd_to_bhsd(q), _bshd_to_bhsd(k), _bshd_to_bhsd(v),
        _bshd_to_bhsd(out), lse, _bshd_to_bhsd(g),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k)
    return (_bhsd_to_bshd(dq, b, h), _bhsd_to_bshd(dk, b, h),
            _bhsd_to_bshd(dv, b, h))


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_fwd(q, k, v, causal=False, scale=None, block_q=None,
                        block_k=None):
    """Public entry: q/k/v (batch, seq, heads, head_dim). ``block_q`` /
    ``block_k`` tune the tile sizes (defaults: DEFAULT_BLOCK_Q/K forward,
    DEFAULT_BWD_BLOCK_Q/K backward)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_attention(q, k, v, causal, scale, block_q, block_k)
