"""Flash attention (forward) as a Pallas TPU kernel.

Replaces the reference's FlashAttention-2 CUDA library integration
(reference: third_party/flashattn; op `flash_attn` at
paddle/phi/ops/yaml/ops.yaml:1635). Design: online-softmax over KV tiles —
grid (batch*heads, q_tiles, kv_tiles) with the kv axis innermost so the
fp32 accumulators in VMEM scratch persist across kv steps; the MXU consumes
(Bq, d) x (d, Bk) tiles; causal tiles above the diagonal are skipped with
@pl.when so no FLOPs are spent on masked blocks.

Backward uses recompute-based VJP (standard flash strategy): the saved
memory is O(B*S*H*d) instead of O(B*H*S^2), and XLA fuses the recomputed
attention with the gradient matmuls.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale, causal, block_q, block_k, seq_q, seq_k):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)
    # Bottom-right-aligned causal diagonal (matches tril(..., k=t-s) in the
    # XLA reference path): query i attends keys <= i + (seq_k - seq_q).
    causal_offset = seq_k - seq_q

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Skip fully-masked tiles (strictly above the causal diagonal).
    run = True
    if causal:
        run = (kv_idx * block_k
               <= q_idx * block_q + (block_q - 1) + causal_offset)

    @pl.when(run)
    def _step():
        q = q_ref[0]          # (block_q, d)
        k = k_ref[0]          # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_k
        if causal:
            mask = mask & (q_pos + causal_offset >= k_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]                      # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                 # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kv_idx == num_kv - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, *, causal, scale, block_q=512, block_k=512):
    """q/k/v: (BH, S, d) -> out (BH, S, d)."""
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    block_q = min(block_q, max(s_q, 8))
    block_k = min(block_k, max(s_k, 8))

    # Pad seq dims to tile multiples and head_dim to the 128-lane width.
    pad_q = (-s_q) % block_q
    pad_k = (-s_k) % block_k
    pad_d = (-d) % 128
    if pad_q or pad_d:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, pad_d)))
    if pad_k or pad_d:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, pad_d)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, pad_d)))
    sp_q, sp_k, dp = s_q + pad_q, s_k + pad_k, d + pad_d

    grid = (bh, sp_q // block_q, sp_k // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_q=s_q, seq_k=s_k)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, sp_q, dp), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, dp), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ],
    )(q, k, v)
    return out[:, :s_q, :d]


def _sdpa_reference(q, k, v, causal, scale):
    """XLA attention used for the recompute VJP (BSHD layout)."""
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, causal, scale):
    b, s, h, d = q.shape
    t = k.shape[1]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out = _flash_fwd_bhsd(qf, kf, vf, causal=causal, scale=scale)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd_rule(q, k, v, causal, scale):
    return _flash_attention(q, k, v, causal, scale), (q, k, v)


def _flash_bwd_rule(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _sdpa_reference(q_, k_, v_, causal,
                                                        scale), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """Public entry: q/k/v (batch, seq, heads, head_dim)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_attention(q, k, v, causal, scale)
