"""In-graph serving kernels: int8 KV (de)quantization + speculative verify.

Siblings to :mod:`.fused_ops`, but these are the serving tier's hot
inner loops (reference: the block_multi_head_attention serving family in
phi/kernels/fusion/ plus PaddleNLP's speculative-decoding verify step).
Both are expressed as pure jnp/lax composites so they fuse into the ONE
jitted engine tick — the paged gather/scatter shapes here are exactly
the ones XLA already lays out well on TPU (vectorized int8<->fp convert
on the VPU, the scale multiply folded into the attention einsum's
prologue), so no hand-written Mosaic kernel is warranted yet; when the
fused ``block_multi_head_attention`` Pallas kernel lands (ROADMAP
roofline item) these helpers define its quantized-page ABI.

* ``kv_quantize_int8`` / ``kv_dequantize_int8`` — symmetric per-token,
  per-KV-head abs-max int8 over the head dim (the ``nn/quant``
  ``weight_only_linear`` pattern applied to KV pages: payload int8,
  sidecar fp scales, dequant at the consumer). Per-(position, head)
  scales keep the quantization error ~0.4% worst-case, small enough
  that greedy decode stays token-identical on the parity gate.
* ``spec_accept_prefix`` — the accept-prefix rule of greedy speculative
  decoding as lax ops: given the target model's per-position greedy
  tokens over ``[last_token, draft...]`` and the draft tokens, count the
  longest matching prefix (bounded per slot by ``max_accept``) so the
  whole verify — draft append, one forward, acceptance — is ONE
  compiled program with a stable ``(B, k+1)`` shape.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["KV_QMAX", "kv_quantize_int8", "kv_dequantize_int8",
           "spec_accept_prefix"]

#: symmetric int8 range for KV payloads (−127..127; −128 unused so the
#: scale inverse is exact for the abs-max element)
KV_QMAX = 127.0


def kv_quantize_int8(x):
    """Quantize KV activations ``(..., D)`` to (int8 payload, scales).

    Scales are per leading element (one per ``(..., )`` position/head
    vector, abs-max over the head dim D) in float32 — the sidecar is
    ``D * itemsize`` times smaller than the payload, so the resident
    page pool still shrinks ~2x vs bf16 (~4x vs fp32).
    """
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / KV_QMAX
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -KV_QMAX, KV_QMAX)
    return q.astype(jnp.int8), scale


def kv_dequantize_int8(q, scale, dtype=jnp.float32):
    """Dequantize an int8 KV payload with its sidecar scales back to
    ``dtype`` (the attention math's accumulation dtype). XLA fuses the
    broadcast multiply into the consuming einsum's operand read."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


def spec_accept_prefix(draft, greedy, max_accept):
    """Greedy speculative-decoding acceptance as ONE lax expression.

    Args:
      draft: ``(B, k)`` int32 draft tokens fed at positions 1..k of the
        verify chunk.
      greedy: ``(B, k+1)`` int32 target-model greedy tokens, where
        ``greedy[:, i]`` is the model's next token after consuming chunk
        position ``i``.
      max_accept: ``(B,)`` int32 per-slot cap on accepted draft tokens
        (0 disables speculation for a slot — e.g. sampling slots, or
        slots butting against a learned-position table).

    Returns ``(n_emit, accepted)`` — ``accepted[b]`` is the length of the
    longest prefix ``i`` with ``draft[b, i] == greedy[b, i]`` (bounded by
    ``max_accept[b]``); ``n_emit = accepted + 1`` because the token after
    the accepted prefix is always the target model's own prediction and
    is emitted unconditionally (the decode step's normal output).
    """
    k = draft.shape[1]
    match = draft == greedy[:, :k]
    match = jnp.logical_and(
        match, jnp.arange(k, dtype=jnp.int32)[None, :]
        < max_accept[:, None].astype(jnp.int32))
    accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                       axis=1)
    return accepted + 1, accepted
