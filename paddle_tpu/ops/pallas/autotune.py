"""Measured per-shape/per-chip kernel autotuning with a persistent cache.

Capability parity with the reference's runtime autotune machinery
(reference: paddle/phi/kernels/autotune/cache.h — AlgorithmsCache keyed by
shape/dtype, paddle/phi/kernels/autotune/switch_autotune.cc — the
enable/disable switch and hit-rate bookkeeping). TPU-native: instead of
picking cuDNN algos, the search picks Pallas tile sizes. First sight of a
(kernel, shape-class, chip) key benchmarks a small candidate grid with the
real compiled kernel, caches the winner in memory AND on disk
(``~/.cache/paddle_tpu/autotune.json`` or ``$PADDLE_TPU_AUTOTUNE_CACHE``),
so later processes on the same chip inherit the measurement instead of a
hand-tuned constant from a different chip generation.

Shape classes bucket the sequence length to the next power of two —
close-by lengths share tiling behavior, so the cache stays small and a
fresh length does not re-benchmark.

The switch is the ``FLAGS_use_autotune`` flag (reference
switch_autotune.cc semantics; default on). When the flag is off or the
backend is not a real TPU (CPU tests run kernels through the Pallas
interpreter, where timing means nothing), callers fall back to their
static defaults.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ...core import flags
from ...observability import metrics as _metrics
from ...observability import trace as _trace

# flags use_autotune / autotune_attn_impl are defined in core/flags.py
# (readers like nn/functional/flash_attention must not depend on this
# module having been imported first)

# Autotune telemetry (gated by FLAGS_enable_metrics)
_m_at_cache = _metrics.counter(
    "paddle_tpu_autotune_cache_total",
    "Autotune winner-cache lookups: hit = cached winner served, miss = "
    "candidate grid measured.", labelnames=("event",))
_m_at_probe_time = _metrics.histogram(
    "paddle_tpu_autotune_measure_seconds",
    "Wall time of one full candidate-grid measurement (all probes).")
_m_at_winner = _metrics.gauge(
    "paddle_tpu_autotune_winner_seconds",
    "Median per-call latency of the winning candidate, per cache key.",
    labelnames=("key",))

__all__ = ["AutotuneCache", "autotune", "cache_path", "chip_kind",
           "seq_bucket", "should_autotune"]


def cache_path() -> str:
    p = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "autotune.json")


def chip_kind() -> str:
    """Device kind string of the default backend, cache-key safe."""
    import jax
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    return str(kind).replace(" ", "_")


def is_tpu_backend() -> bool:
    """True only for backends whose Pallas timings are meaningful tile
    probes. Positive list, not "not cpu": a GPU (or any other) backend
    must not run TPU tile probes and cache their winners."""
    import jax
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def should_autotune() -> bool:
    """Autotune only where measuring is meaningful: flag on + real chip
    (the Pallas interpreter's timings would tune for the interpreter)."""
    return bool(flags.get_flag("use_autotune")) and is_tpu_backend()


def probe_reps(flops_per_call: float, target_s: float = 0.08,
               assumed_tflops: float = 100.0) -> int:
    """How many times to chain a kernel inside one probe program so
    device time dominates per-call dispatch/transport overhead (remote
    tunnels have a ~100 ms floor that would otherwise bury the kernel)."""
    per_call_s = max(flops_per_call, 1.0) / (assumed_tflops * 1e12)
    return int(min(256, max(4, round(target_s / per_call_s))))


def seq_bucket(n: int) -> int:
    """Next power of two ≥ n (min 128): nearby lengths share tiling."""
    b = 128
    while b < n:
        b *= 2
    return b


#: bump when the measurement methodology or entry layout changes — every
#: entry stamped with an older schema is treated as absent and re-measured
#: (a winner tuned under old methodology must not survive the upgrade)
SCHEMA_VERSION = 2


class AutotuneCache:
    """Process-wide winner cache, mirrored to a JSON file.

    File writes are atomic (tmp + rename) and merged with any concurrent
    writer's content at save time (last writer wins per key) — several
    processes on one host converge instead of clobbering each other.

    Entries are stamped ``{"schema": SCHEMA_VERSION, "stamp": epoch_s,
    "value": winner}``; ``get`` unwraps the stamp and returns ``None``
    for entries from another schema (including pre-stamp bare values),
    so stale winners invalidate instead of silently persisting.
    """

    def __init__(self, path: Optional[str] = None):
        self._path = path or cache_path()
        self._lock = threading.Lock()
        self._mem: Dict[str, Any] = {}
        self._loaded = False

    # ------------------------------------------------------------- file io
    def _load_file(self) -> Dict[str, Any]:
        try:
            with open(self._path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def _ensure_loaded(self):
        if not self._loaded:
            disk = self._load_file()
            disk.update(self._mem)  # in-memory results win
            self._mem = disk
            self._loaded = True

    def _save(self):
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            merged = self._load_file()
            merged.update(self._mem)
            tmp = f"{self._path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
            os.replace(tmp, self._path)
        except OSError:
            pass  # cache persistence is best-effort

    # -------------------------------------------------------------- access
    def get(self, key: str):
        with self._lock:
            self._ensure_loaded()
            ent = self._mem.get(key)
        if isinstance(ent, dict) and "schema" in ent:
            if ent.get("schema") != SCHEMA_VERSION:
                return None  # stamped under another methodology: stale
            return ent.get("value")
        # pre-stamp bare value (or absent): treat as stale either way
        return None

    def put(self, key: str, value, persist: bool = True):
        with self._lock:
            self._ensure_loaded()
            self._mem[key] = {"schema": SCHEMA_VERSION,
                              "stamp": time.time(), "value": value}
            if persist:
                self._save()

    def clear_memory(self):
        """Forget in-process state (tests); disk is untouched."""
        with self._lock:
            self._mem = {}
            self._loaded = False


_cache = AutotuneCache()


def get_cache() -> AutotuneCache:
    return _cache


def make_key(kernel: str, **attrs) -> str:
    parts = [kernel, chip_kind()]
    parts += [f"{k}={attrs[k]}" for k in sorted(attrs)]
    return "|".join(parts)


def _value_sync(x) -> None:
    """Force the computation to COMPLETE, by value read. On tunneled /
    remote-dispatch backends ``block_until_ready`` returns before the
    device has actually executed (it drains the local client only), so
    timing loops must read a value derived from the result."""
    import jax
    import jax.numpy as jnp
    try:
        float(jnp.sum(x))  # tpulint: disable=TPU103 — deliberate host sync: _value_sync exists to force device completion for timing
    except TypeError:
        jax.block_until_ready(x)


def autotune(key: str,
             candidates: Sequence[Any],
             run: Callable[[Any, int], Any],
             default: Any,
             warmup: int = 2,
             iters: int = 5) -> Any:
    """Return the cached winner for ``key``, measuring on first sight.

    ``run(candidate, i)`` executes the kernel once with that candidate on
    the ``i``-th probe input and returns a JAX value. Callers must pass
    per-candidate JITTED closures over a few DISTINCT probe inputs —
    timing re-traced calls measures Python, and repeating one identical
    execution lets replay-caching backends fake the timing. Candidates
    that fail to compile or run are skipped; if all fail, ``default`` is
    cached so the failure is not re-paid every call.
    """
    cached = _cache.get(key)
    if cached is not None:
        if _metrics.enabled():
            _m_at_cache.inc(event="hit")
        # JSON round-trips tuples as lists
        return tuple(cached) if isinstance(cached, list) else cached

    if _metrics.enabled():
        _m_at_cache.inc(event="miss")
    measure_t0 = time.perf_counter()
    best, best_t = None, float("inf")
    timings = {}
    with _trace.span(f"autotune:{key}", "autotune",
                     {"candidates": len(candidates)}):
        for cand in candidates:
            try:
                for i in range(max(warmup, 1)):
                    _value_sync(run(cand, i))
                ts = []
                for i in range(iters):
                    t0 = time.perf_counter()
                    _value_sync(run(cand, warmup + i))
                    ts.append(time.perf_counter() - t0)
                ts.sort()
                dt = ts[len(ts) // 2]
            except Exception:
                continue
            timings[str(cand)] = dt
            if dt < best_t:
                best, best_t = cand, dt
    if _metrics.enabled():
        _m_at_probe_time.observe(time.perf_counter() - measure_t0)
        if best is not None:
            _m_at_winner.set(best_t, key=key)
    if flags.get_flag("log_level") >= 1:
        import logging
        ranked = ", ".join(f"{c}={t * 1e3:.3f}ms" for c, t in
                           sorted(timings.items(), key=lambda kv: kv[1]))
        logging.getLogger("paddle_tpu.autotune").info(
            "autotune %s: %s", key, ranked or "no candidate survived")
    if best is None:
        best = default
    _cache.put(key, list(best) if isinstance(best, tuple) else best)
    return best
