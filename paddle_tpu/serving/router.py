"""Multi-replica serving router — the tier's front door.

Fronts R :class:`~paddle_tpu.inference.PagedEngine` replicas with one
``add_request``/``step``/``stream``/``drain_outcomes`` surface (the same
duck type as a single engine, so ``tools/loadgen.py`` drives a router
and a replica identically). Policy, in order:

* **Admission keys on the round-11 probes** — only ``READY`` replicas
  receive new traffic; a ``DEGRADED``/``DRAINING``/``WARMING`` replica
  drops out of rotation the moment its lifecycle flips, no health-check
  polling loop required (the probes ARE the state machine).
* **Load balancing on queue depth** — candidates are ordered by
  ``health()`` backlog (queued + active), so a slow replica sheds load
  to its peers instead of building a deep queue.
* **Backpressure retry** — a replica's bounded admission queue raising
  :class:`Overloaded` bounces the request to the next candidate; the
  submitter never sees a replica-level rejection.
* **Shed at the router, never inside a replica** — when every candidate
  is saturated (or none is READY), the request becomes a router-level
  ``SHED`` outcome without ever touching a replica queue. Replicas run
  with shedding disabled in router deployments: the tier's overload
  policy lives in ONE place, and a replica's queue never buries work
  the router could have redirected.
* **Re-routing** — a request stranded by a replica failure (tick-crash
  ``FAILED``) or a drain-before-admission ``CANCELLED`` is resubmitted
  to another replica with its already-generated tokens as prompt
  prefix: paid-for prefill/decode work is carried, not discarded, and
  the client-visible outcome/stream just continues.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..inference.resilience import (Overloaded, RequestOutcome,
                                    RequestStatus, TERMINAL_STATUSES)
from ..observability import metrics as _metrics
from ..observability import reqtrace as _reqtrace
from .stream import TokenStream

__all__ = ["RouterConfig", "Router"]

#: default router-name ordinals (stable within one process, like the
#: replica counter in inference/resilience.py)
_ROUTER_COUNTER = itertools.count(0)


M_ROUTER_ROUTED = _metrics.counter(
    "paddle_tpu_serving_router_routed_total",
    "Requests the router admitted into a replica, by replica name.",
    labelnames=("replica",))
M_ROUTER_RETRIES = _metrics.counter(
    "paddle_tpu_serving_router_retries_total",
    "Submit attempts bounced by replica Overloaded backpressure and "
    "retried on the next candidate.")
M_ROUTER_SHED = _metrics.counter(
    "paddle_tpu_serving_router_shed_total",
    "Requests shed at the router because no READY replica could admit "
    "them (replicas never saw these).")
M_ROUTER_REROUTED = _metrics.counter(
    "paddle_tpu_serving_router_rerouted_total",
    "Requests re-routed to another replica after a replica failure or "
    "drain stranded them mid-flight.")


@dataclass
class RouterConfig:
    """``max_reroutes``: per-request bound on failure re-routes before
    the stranding outcome is surfaced to the client (defaults to the
    replica count). ``reroute_failed`` / ``reroute_drained``: which
    stranding outcomes are retried. The ``slo_*`` knobs feed the
    tier-level ``paddle_tpu_serving_slo_{fast,slow}_burn_rate`` gauges
    (scope = the router's name) — the client-visible SLO lives HERE,
    where shedding happens, not per replica."""

    max_reroutes: Optional[int] = None
    reroute_failed: bool = True
    reroute_drained: bool = True
    slo_target: float = 0.99
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0


@dataclass
class _RoutedRequest:
    """Router-side bookkeeping for one client request across replicas."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float
    top_p: float
    ttft_deadline_s: Optional[float]
    deadline_s: Optional[float]
    submit_t: float
    tokens: List[int] = field(default_factory=list)
    token_times: List[float] = field(default_factory=list)
    first_token_t: Optional[float] = None
    replica_idx: Optional[int] = None
    replica_rid: Optional[int] = None
    reroutes: int = 0
    stream_buf: Optional[List[int]] = None    # router-level delta buffer
    _rep_buf: Optional[List[int]] = None      # current replica's buffer
    _rep_read: int = 0


class Router:
    """Route client requests across R paged-engine replicas.

    The router is single-threaded like the engines it fronts: ``step()``
    ticks every replica with work, then settles outcomes (collect,
    re-route, record). It keeps only live bookkeeping plus undrained
    outcomes — the same retention contract as one replica.
    """

    def __init__(self, replicas, *, config: Optional[RouterConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: Optional[str] = None):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        self.replicas = list(replicas)
        self.config = config or RouterConfig()
        self._clock = clock
        #: stable reqtrace scope / SLO-gauge label for this tier
        self.name = name if name is not None else \
            f"router{next(_ROUTER_COUNTER)}"
        self._slo = _reqtrace.SloTracker(
            self.name, target=self.config.slo_target,
            fast_window_s=self.config.slo_fast_window_s,
            slow_window_s=self.config.slo_slow_window_s)
        self._rid = 0
        self._live: Dict[Tuple[int, int], _RoutedRequest] = {}
        self._by_rid: Dict[int, _RoutedRequest] = {}
        #: terminal outcome per router-level request id
        self.outcomes: Dict[int, RequestOutcome] = {}
        self.per_replica = [
            {"routed": 0, "finished": 0, "good_tokens": 0, "rerouted_away": 0}
            for _ in self.replicas]
        self.shed_at_router = 0
        self._draining = False

    # ------------------------------------------------------------ policy
    def _candidates(self) -> List[int]:
        """READY replicas, least-loaded first (queue depth + active)."""
        scored = []
        for i, rep in enumerate(self.replicas):
            if not rep.lifecycle.ready():
                continue
            h = rep.health()
            scored.append((h["queue_depth"] + h["active"], i))
        scored.sort()
        return [i for _, i in scored]

    def _max_reroutes(self) -> int:
        mr = self.config.max_reroutes
        return len(self.replicas) if mr is None else mr

    # ------------------------------------------------- request tracing
    @property
    def reqtrace_scope(self) -> str:
        """Timeline scope tier-level events record under; replica legs
        are joined through the ``routed`` events (reqtrace.stitch)."""
        return self.name

    def _rt_event(self, rid: int, event: str,
                  t: Optional[float] = None, **meta):
        _reqtrace.emit(self.name, self._clock, rid, event, t, **meta)

    # --------------------------------------------------------------- API
    def warmup(self) -> "Router":
        for rep in self.replicas:
            rep.warmup()
        return self

    def add_request(self, prompt_ids, max_new_tokens: int = 32,
                    temperature: float = 0.0, top_p: float = 1.0,
                    ttft_deadline_s: Optional[float] = None,
                    deadline_s: Optional[float] = None) -> int:
        """Admit one request into the tier; returns the router-level
        request id. Never raises for overload — a request no replica can
        take becomes a router-level ``SHED`` outcome (the router is
        where the tier sheds; clients poll/stream by rid either way)."""
        self._rid += 1
        rr = _RoutedRequest(
            rid=self._rid, prompt=[int(t) for t in prompt_ids],
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_p=top_p, ttft_deadline_s=ttft_deadline_s,
            deadline_s=deadline_s, submit_t=self._clock())
        self._by_rid[rr.rid] = rr
        self._rt_event(rr.rid, "submitted", t=rr.submit_t,
                       prompt_tokens=len(rr.prompt),
                       max_new_tokens=max_new_tokens,
                       ttft_deadline_s=ttft_deadline_s,
                       deadline_s=deadline_s)
        if not self._try_submit(rr):
            # the shed CAUSE gets a timestamped event of its own (not
            # just the terminal outcome), so a shed storm's timelines
            # say which rotation state refused the tier's traffic
            ready = sum(1 for rep in self.replicas
                        if rep.lifecycle.ready())
            self._rt_event(rr.rid, "shed",
                           ready_replicas=ready,
                           replicas=len(self.replicas))
            self.shed_at_router += 1
            M_ROUTER_SHED.inc()
            self._finish(rr, RequestStatus.SHED,
                         detail="router: no READY replica could admit "
                                "(all saturated or out of rotation)")
        return rr.rid

    def _try_submit(self, rr: _RoutedRequest, exclude=()) -> bool:
        """Submit ``rr`` (or its continuation) to the best candidate;
        False when every candidate refused."""
        remaining = rr.max_new_tokens - len(rr.tokens)
        prompt = rr.prompt + rr.tokens
        bounced = 0
        for i in self._candidates():
            if i in exclude:
                continue
            rep = self.replicas[i]
            try:
                rrid = rep.add_request(
                    prompt, max_new_tokens=remaining,
                    temperature=rr.temperature, top_p=rr.top_p,
                    ttft_deadline_s=rr.ttft_deadline_s,
                    deadline_s=rr.deadline_s)
            except Overloaded:
                M_ROUTER_RETRIES.inc()
                bounced += 1
                continue
            # submit-time terminal (never-fitting geometry): surface it
            # from this replica rather than looping the tier
            rr.replica_idx, rr.replica_rid = i, rrid
            self._live[(i, rrid)] = rr
            self.per_replica[i]["routed"] += 1
            self._rt_event(rr.rid, "routed",
                           replica=rep.lifecycle.name,
                           replica_rid=rrid,
                           tokens_carried=len(rr.tokens),
                           overloaded_bounces=bounced)
            M_ROUTER_ROUTED.inc(replica=rep.lifecycle.name)
            if rr.stream_buf is not None:
                rr._rep_buf = rep.open_stream(rrid)
                rr._rep_read = 0
            return True
        return False

    def has_work(self) -> bool:
        if any(rep.has_work() for rep in self.replicas):
            return True
        # a replica drained/crashed outside step() may hold terminal
        # outcomes of ours that still need settling (and possibly
        # re-routing) — that is work for the next tick
        return any((i, rrid) in self._live
                   for i, rep in enumerate(self.replicas)
                   for rrid in rep.outcomes)

    def step(self) -> Dict[int, List[int]]:
        """One tier tick: tick every replica with work, then settle
        outcomes. Returns {router_rid: full_token_list} for requests
        that FINISHED this tick."""
        for rep in self.replicas:
            if rep.has_work() and rep.lifecycle.live():
                rep.step()
        self._pump_streams()
        return self._settle()

    def run_to_completion(self, max_ticks: int = 10_000) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        ticks = 0
        while self.has_work():
            out.update(self.step())
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError("router did not converge")
        return out

    # ---------------------------------------------------------- settling
    def _pump_streams(self):
        """Move per-tick token deltas replica buffer -> router buffer
        for requests with an open stream."""
        for rr in self._live.values():
            if rr.stream_buf is None or rr._rep_buf is None:
                continue
            new = rr._rep_buf[rr._rep_read:]
            if new:
                rr._rep_read += len(new)
                rr.stream_buf.extend(new)

    def _settle(self) -> Dict[int, List[int]]:
        finished: Dict[int, List[int]] = {}
        for i, rep in enumerate(self.replicas):
            for rrid, oc in rep.drain_outcomes().items():
                rr = self._live.pop((i, rrid), None)
                if rr is None:
                    continue       # not ours (e.g. direct submissions)
                self._absorb(rr, oc, i, finished)
        return finished

    def _absorb(self, rr: _RoutedRequest, oc: RequestOutcome,
                replica_idx: int, finished: Dict[int, List[int]]):
        rr.tokens.extend(oc.tokens)
        rr.token_times.extend(oc.token_times)
        if rr.first_token_t is None:
            rr.first_token_t = oc.first_token_t
        rr.replica_idx = rr.replica_rid = None
        rr._rep_buf, rr._rep_read = None, 0
        if oc.status == RequestStatus.FINISHED:
            self.per_replica[replica_idx]["finished"] += 1
            self.per_replica[replica_idx]["good_tokens"] += len(oc.tokens)
            self._finish(rr, RequestStatus.FINISHED)
            finished[rr.rid] = list(rr.tokens)
            return
        if (self._should_reroute(oc)
                and rr.reroutes < self._max_reroutes()
                and len(rr.tokens) < rr.max_new_tokens):
            rr.reroutes += 1
            self.per_replica[replica_idx]["rerouted_away"] += 1
            self._rt_event(
                rr.rid, "rerouted",
                from_replica=self.replicas[replica_idx].lifecycle.name,
                stranding_outcome=oc.status, stranding_detail=oc.detail,
                tokens_carried=len(rr.tokens), reroutes=rr.reroutes)
            M_ROUTER_REROUTED.inc()
            if self._try_submit(rr, exclude=(replica_idx,)):
                return
            # nobody else could take it — surface the stranding outcome
            self._finish(rr, oc.status,
                         detail=f"re-route failed: {oc.detail}")
            return
        self._finish(rr, oc.status, detail=oc.detail)

    def _should_reroute(self, oc: RequestOutcome) -> bool:
        cfg = self.config
        if self._draining:
            # a tier-level drain cancels everywhere at once — counting
            # (and failing) a re-route per stranded request would be
            # phantom telemetry; the CANCELLED outcome passes through
            return False
        if oc.status == RequestStatus.FAILED:
            return cfg.reroute_failed and "blocks" not in oc.detail
        if oc.status == RequestStatus.CANCELLED:
            return cfg.reroute_drained and "drain" in oc.detail
        return False

    def _finish(self, rr: _RoutedRequest, status: str, detail: str = ""):
        finish_t = self._clock()
        self._rt_event(rr.rid, "terminal", t=finish_t, outcome=status,
                       detail=detail, tokens=len(rr.tokens))
        self._slo.note(finish_t, good=(status == RequestStatus.FINISHED))
        self.outcomes[rr.rid] = RequestOutcome(
            rid=rr.rid, status=status, detail=detail,
            tokens=list(rr.tokens), submit_t=rr.submit_t,
            first_token_t=rr.first_token_t, finish_t=finish_t,
            token_times=list(rr.token_times))
        self._by_rid.pop(rr.rid, None)

    # --------------------------------------------------------- inspection
    def request_status(self, rid: int) -> Optional[str]:
        oc = self.outcomes.get(rid)
        if oc is not None:
            return oc.status
        rr = self._by_rid.get(rid)
        if rr is None:
            return None
        if rr.replica_idx is not None:
            st = self.replicas[rr.replica_idx].request_status(rr.replica_rid)
            if st is not None:
                return st
        return RequestStatus.QUEUED

    def drain_outcomes(self) -> Dict[int, RequestOutcome]:
        out, self.outcomes = self.outcomes, {}
        return out

    def stream(self, rid: int) -> TokenStream:
        """Incremental token stream for a live (or just-submitted)
        request; survives re-routing — the stream keeps yielding across
        a replica hand-off."""
        rr = self._by_rid.get(rid)
        buf: List[int] = []
        if rr is not None:
            if rr.stream_buf is None:
                # late attach replays the whole completion so far:
                # tokens carried from previous replicas (re-routes fold
                # them into rr.tokens), then the current replica's
                rr.stream_buf = list(rr.tokens)
                if rr.replica_idx is not None:
                    rep = self.replicas[rr.replica_idx]
                    rr._rep_buf = rep.open_stream(rr.replica_rid)
                    rr._rep_read = 0
                    rr.stream_buf.extend(rr._rep_buf)
                    rr._rep_read = len(rr._rep_buf)
            buf = rr.stream_buf
        else:
            oc = self.outcomes.get(rid)
            if oc is not None:
                buf = list(oc.tokens)
        return TokenStream(
            rid, buf, self.step, lambda: self.request_status(rid),
            lambda s: s in TERMINAL_STATUSES,
            trace_hook=lambda ev, **meta: self._rt_event(rid, ev, **meta))

    def drain(self) -> Dict[int, List[int]]:
        """Drain every replica and settle all remaining outcomes."""
        self._draining = True
        for rep in self.replicas:
            if rep.lifecycle.live():
                rep.drain()
        finished: Dict[int, List[int]] = {}
        self._pump_streams()
        finished.update(self._settle())
        # anything still live points at a stopped replica: terminal
        for key, rr in list(self._live.items()):
            self._live.pop(key)
            self._finish(rr, RequestStatus.CANCELLED,
                         detail="router drained")
        return finished

    def health(self) -> dict:
        """Tier-level health: aggregate + per-replica probe payloads."""
        reps = [rep.health() for rep in self.replicas]
        return {
            "replicas": len(self.replicas),
            "ready": sum(1 for rep in self.replicas
                         if rep.lifecycle.ready()),
            "live": sum(1 for rep in self.replicas
                        if rep.lifecycle.live()),
            "queue_depth": sum(h["queue_depth"] for h in reps),
            "active": sum(h["active"] for h in reps),
            "shed_at_router": self.shed_at_router,
            # probe-path burn-rate decay poll (see PagedEngine.health)
            "slo_burn_rate": self._slo.burn_rates(self._clock()),
            "per_replica": reps,
        }

    def stats(self) -> dict:
        """Routing breakdown for load reports (loadgen --replicas)."""
        return {
            "shed_at_router": self.shed_at_router,
            "per_replica": [
                {"replica": rep.lifecycle.name, **counts,
                 "state": rep.lifecycle.state}
                for rep, counts in zip(self.replicas, self.per_replica)],
        }
