"""N-gram speculative decoding: the host-side draft proposer.

Speculative decoding splits a decode step into *draft* (cheap guesses at
the next k tokens) and *verify* (one target-model forward over all k+1
positions, accepting the longest prefix the model agrees with). The
verify is the expensive half and it lives IN-GRAPH in the engine — draft
tokens appended to the decode feed, one paged-attention forward, and the
accept-prefix rule as lax ops (``ops.pallas.serving.spec_accept_prefix``),
so the whole step is ONE compiled program with the stable shape
``(max_batch, k+1)``.

This module is the draft half. The n-gram proposer (the "prompt lookup
decoding" trick) needs no draft model: it matches the sequence's own
trailing n-gram against its earlier history and proposes the tokens that
followed last time. On natural text and code the continuation repeats
often enough for 2-4x decode speedups at zero extra weights; when it
misses, the verify emits exactly the token normal decode would have —
speculation never changes greedy output, only how many tokens one
program yields.

Proposers are pluggable: anything with ``propose(context) -> list[int]``
(at most ``k`` tokens) slots into ``PagedEngine(speculate=...)`` — a
draft-model proposer rides the same verify program.
"""
from __future__ import annotations

from typing import List, Sequence

from ..observability import metrics as _metrics

__all__ = ["NgramProposer", "M_SPEC_PROPOSED", "M_SPEC_ACCEPTED",
           "M_SPEC_ACCEPT_RATE"]


M_SPEC_PROPOSED = _metrics.counter(
    "paddle_tpu_serving_spec_proposed_tokens_total",
    "Draft tokens proposed into speculative verify steps.")
M_SPEC_ACCEPTED = _metrics.counter(
    "paddle_tpu_serving_spec_accepted_tokens_total",
    "Draft tokens accepted by the target model (each saves one decode "
    "tick).")
M_SPEC_ACCEPT_RATE = _metrics.gauge(
    "paddle_tpu_serving_spec_acceptance_rate",
    "Cumulative accepted/proposed draft-token ratio of this process's "
    "speculative engines.")


class NgramProposer:
    """Draft ``k`` tokens by n-gram lookup in the request's own history.

    Tries the longest trailing n-gram first (``max_n`` down to
    ``min_n``): scan the context right-to-left for the most recent
    earlier occurrence, and propose the tokens that followed it. Returns
    at most ``k`` tokens; fewer (or none) when history has no match —
    the engine pads the verify feed and caps acceptance, so a dry
    proposer costs one ordinary decode step, nothing more.

    The scan is O(len(context)) per call with early exit on the first
    (most recent) match — fine for serving-length contexts; a rolling
    hash index is the upgrade path if profiles ever show it.
    """

    def __init__(self, k: int = 4, max_n: int = 3, min_n: int = 1):
        if k < 1:
            raise ValueError("k must be >= 1")
        if not 1 <= min_n <= max_n:
            raise ValueError("need 1 <= min_n <= max_n")
        self.k = k
        self.max_n = max_n
        self.min_n = min_n

    def propose(self, context: Sequence[int]) -> List[int]:
        ctx = list(context)
        L = len(ctx)
        for n in range(min(self.max_n, L - 1), self.min_n - 1, -1):
            tail = ctx[L - n:]
            # most recent earlier occurrence of the trailing n-gram
            for j in range(L - n - 1, -1, -1):
                if ctx[j:j + n] == tail:
                    cont = ctx[j + n:j + n + self.k]
                    if cont:
                        return cont
        return []
