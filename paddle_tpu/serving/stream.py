"""Per-request incremental token streams.

A serving front door returns tokens as they are generated, not a batch
at completion. :class:`TokenStream` is the shared iterator behind
``PagedEngine.stream(rid)`` and ``Router.stream(rid)``: it reads a delta
buffer the producer appends to every tick, pumps the producer's
``step()`` while the buffer is dry, and terminates exactly when the
request reaches a terminal status — ``stream.status`` then holds it
(``FINISHED``, or the degraded outcome: ``SHED`` / ``DEADLINE_MISSED``
/ ``CANCELLED`` / ``FAILED``). Nothing raises out of iteration; a
stream over a request cancelled by a replica drain simply stops, with
the terminal status readable — the same nothing-raises contract as the
tick loop itself.
"""
from __future__ import annotations

from typing import Callable, List, Optional

__all__ = ["TokenStream"]


class TokenStream:
    """Iterator over one request's tokens as they are generated.

    Args:
      buf: the shared delta list the producer appends tokens to.
      pump: advances the producer one tick (``engine.step`` /
        ``router.step``); called only while the request is live and the
        buffer has no unread tokens.
      status_fn: returns the request's current status string, or ``None``
        once unknown (e.g. outcomes drained elsewhere — treated as
        terminal).
      is_terminal: predicate over status strings.
      max_pumps: backstop on consecutive dry pumps between tokens — a
        wedged producer must fail the stream, not hang the client.
      trace_hook: optional request-trace seam (the producer binds its
        reqtrace scope/clock): called as ``hook(event, **meta)`` with
        ``first_delivery`` when the first token reaches the client and
        ``stream_closed`` at termination — the delivery half of the
        request timeline (tokens can sit generated-but-unread when a
        client attaches late or reads slowly).
    """

    def __init__(self, rid: int, buf: List[int], pump: Callable[[], object],
                 status_fn: Callable[[], Optional[str]],
                 is_terminal: Callable[[Optional[str]], bool],
                 max_pumps: int = 10_000,
                 trace_hook: Optional[Callable[..., None]] = None):
        self.rid = rid
        self.status: Optional[str] = None
        self._buf = buf
        self._pump = pump
        self._status_fn = status_fn
        self._is_terminal = is_terminal
        self._max_pumps = max_pumps
        self._read = 0
        self._final_pump_done = False
        self._trace = trace_hook

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        pumps = 0
        while True:
            if self._read < len(self._buf):
                tok = self._buf[self._read]
                self._read += 1
                if self._read == 1 and self._trace is not None:
                    self._trace("first_delivery",
                                buffered=len(self._buf))
                return tok
            status = self._status_fn()
            if status is None or self._is_terminal(status):
                # one last pump so trailing tokens parked between the
                # producer and this buffer (e.g. a replica drained
                # outside the router's step loop) flow in, then drain
                # whatever arrived before closing
                if not self._final_pump_done:
                    self._final_pump_done = True
                    self._pump()
                if self._read < len(self._buf):
                    continue
                self.status = self._status_fn() or status
                if self._trace is not None:
                    self._trace("stream_closed", status=self.status,
                                delivered=self._read)
                    self._trace = None      # close exactly once
                raise StopIteration
            pumps += 1
            if pumps > self._max_pumps:
                # close the timeline's delivery half BEFORE raising: a
                # wedged producer is exactly the failure the request
                # flight recorder exists to diagnose, and an open-ended
                # stream mark would read as a client that walked away
                if self._trace is not None:
                    self._trace("stream_closed", status=status,
                                delivered=self._read,
                                error=f"no progress in "
                                      f"{self._max_pumps} pumps")
                    self._trace = None
                raise RuntimeError(
                    f"stream for request {self.rid} made no progress in "
                    f"{self._max_pumps} ticks (status {status})")
            self._pump()

    def drain(self) -> List[int]:
        """Consume the rest of the stream and return it as a list."""
        return list(self)
