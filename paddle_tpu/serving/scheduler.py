"""Phase-split tick scheduling: chunked prefill budgeted against decode.

The pathology (Sarathi-Serve names it): a continuous-batching engine
that prefills every admitted prompt to completion inside the admission
tick stalls the decode batch for the whole prompt length — one 2k-token
prompt freezes every in-flight stream's inter-token latency. The fix the
production stacks converged on (Sarathi chunked prefill, DistServe
prefill/decode disaggregation): prompts advance in fixed ``block_size``
chunks under a per-tick token budget, and the batched decode step runs
EVERY tick regardless of pending prefill — decode has priority, prefill
gets the leftover budget.

:class:`Scheduler` owns that budget arithmetic plus the phase
accounting; the engine asks it ``chunk_quota()`` each tick and reports
every chunk/decode program it runs. ``prefill_token_budget=None`` keeps
the round-3 behavior (drain all pending chunks in the admission tick) —
single-replica batch jobs that only care about completion throughput
lose nothing, while a router-fronted replica sets a budget and holds
inter-token latency through prompt bursts.

Metrics (stable rows, see README "Serving tier"):
``paddle_tpu_serving_prefill_tokens_total`` /
``paddle_tpu_serving_decode_tokens_total`` count scheduled tokens per
phase; ``paddle_tpu_serving_tick_phase_share{phase=}`` is the sliding
share of device time each phase took over recent ticks — the signal a
capacity planner reads to split a fleet into prefill- and decode-heavy
replica pools (the DistServe topology) without re-instrumenting.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..observability import metrics as _metrics

__all__ = ["SchedulerConfig", "Scheduler"]


M_PREFILL_TOKENS = _metrics.counter(
    "paddle_tpu_serving_prefill_tokens_total",
    "Prompt tokens scheduled through chunked prefill (includes chunk "
    "padding — the tokens the chip actually processed).")
M_DECODE_TOKENS = _metrics.counter(
    "paddle_tpu_serving_decode_tokens_total",
    "Tokens scheduled through the batched decode step (speculative "
    "verify positions count — they are decode compute).")
M_TICK_PHASE_SHARE = _metrics.gauge(
    "paddle_tpu_serving_tick_phase_share",
    "Sliding share of per-tick device time spent in each serving phase "
    "(prefill vs decode), over the last window of ticks.",
    labelnames=("phase",))
M_PREFILL_DEFERRED = _metrics.counter(
    "paddle_tpu_serving_prefill_chunks_deferred_total",
    "Prefill chunks ready to run but pushed to a later tick by the "
    "scheduler's token budget (decode-priority interleaving at work).")


@dataclass
class SchedulerConfig:
    """Knobs for the phase-split tick scheduler.

    ``prefill_token_budget``
        Upper bound on prompt tokens advanced per tick across the batch
        (each scheduled chunk-slot costs ``block_size`` tokens). ``None``
        disables the split: admitted prompts prefill to completion in
        their admission tick (the round-3 behavior).
    ``min_prefill_chunks``
        Progress guarantee: even when the budget is smaller than one
        chunk, at least this many chunk-slots run per tick while prefill
        work is pending — a budget can interleave, never livelock.
    ``share_window_ticks``
        Ticks in the sliding window behind the phase-share gauge.
    """

    prefill_token_budget: Optional[int] = None
    min_prefill_chunks: int = 1
    share_window_ticks: int = 32

    def __post_init__(self):
        if (self.prefill_token_budget is not None
                and self.prefill_token_budget < 1):
            raise ValueError("prefill_token_budget must be >= 1 or None")
        if self.min_prefill_chunks < 1:
            raise ValueError("min_prefill_chunks must be >= 1")
        if self.share_window_ticks < 1:
            raise ValueError("share_window_ticks must be >= 1")


class Scheduler:
    """Budgets each engine tick between chunked prefill and decode and
    keeps the per-phase accounting (tokens, device seconds, tick share).

    One scheduler belongs to one engine; the engine drives it:
    ``chunk_quota`` at the top of the prefill pass, ``note_phase`` after
    every compiled program, ``end_tick`` when the tick closes.
    """

    def __init__(self, config: Optional[SchedulerConfig] = None):
        self.config = config or SchedulerConfig()
        #: lifetime token totals per phase (mirrors the counters, local
        #: so health()/bench can read them without the metrics registry)
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.deferred_chunks = 0
        self._window = []          # (prefill_s, decode_s) per tick
        self._tick_s = {"prefill": 0.0, "decode": 0.0}

    # ------------------------------------------------------------ budget
    def chunk_quota(self, block_size: int) -> Optional[int]:
        """Chunk-slots (``block_size`` tokens each) this tick may spend
        on prefill; ``None`` = unbounded (no phase split configured)."""
        budget = self.config.prefill_token_budget
        if budget is None:
            return None
        return max(self.config.min_prefill_chunks, budget // block_size)

    def note_deferred(self, chunks: int):
        if chunks > 0:
            self.deferred_chunks += chunks
            M_PREFILL_DEFERRED.inc(chunks)

    def tick_phase_seconds(self) -> dict:
        """The CURRENT tick's accumulated per-phase device seconds
        (before ``end_tick`` folds them into the window). The engine
        stamps this onto its ``serving.tick`` span, so the chrome view
        ``tools/request_trace.py`` merges shows each tick's
        prefill/decode split next to the request lanes."""
        return dict(self._tick_s)

    # -------------------------------------------------------- accounting
    def note_phase(self, phase: str, tokens: int, seconds: float):
        """One compiled program ran: ``tokens`` scheduled positions in
        ``phase`` took ``seconds`` of (blocking-read bracketed) time."""
        if phase == "prefill":
            self.prefill_tokens += tokens
            M_PREFILL_TOKENS.inc(tokens)
        else:
            self.decode_tokens += tokens
            M_DECODE_TOKENS.inc(tokens)
        self._tick_s[phase if phase in self._tick_s else "decode"] += \
            seconds

    def end_tick(self):
        """Close the tick: fold its phase seconds into the sliding
        window and export the share gauges."""
        cur = (self._tick_s["prefill"], self._tick_s["decode"])
        self._tick_s = {"prefill": 0.0, "decode": 0.0}
        if cur == (0.0, 0.0):
            return
        self._window.append(cur)
        if len(self._window) > self.config.share_window_ticks:
            self._window.pop(0)
        p = sum(w[0] for w in self._window)
        d = sum(w[1] for w in self._window)
        total = p + d
        if total > 0:
            M_TICK_PHASE_SHARE.set(p / total, phase="prefill")
            M_TICK_PHASE_SHARE.set(d / total, phase="decode")

    def phase_share(self) -> dict:
        """The gauge values as a dict (for ``health()`` / bench)."""
        p = sum(w[0] for w in self._window)
        d = sum(w[1] for w in self._window)
        total = p + d
        return {"prefill": (p / total) if total else None,
                "decode": (d / total) if total else None}
