"""paddle_tpu.serving — the multi-replica LLM serving tier.

The deployment story joining five shipped subsystems (see README
"Serving tier"): the continuous-batching :class:`~paddle_tpu.inference.
PagedEngine` is the data plane; this package adds the control plane that
turns one replica into an operable tier:

* :mod:`.scheduler` — phase-split tick scheduling (Sarathi-style chunked
  prefill under a per-tick token budget, decode-priority so long prompts
  stop stalling decode batches) + the per-phase token/tick-share metrics.
* :mod:`.speculative` — the n-gram draft proposer behind the engine's
  ``speculate=`` knob; the fused single-program verify step itself lives
  in the engine (``ops/pallas/serving.spec_accept_prefix``).
* :mod:`.stream` — per-request incremental token streams
  (``engine.stream(rid)`` / ``router.stream(rid)``).
* :mod:`.router` — the multi-replica front door: admission keyed on the
  round-11 readiness probes, queue-depth load balancing, ``Overloaded``
  retry on the next replica, re-routing of requests stranded by a
  degraded/drained replica, and load shedding AT THE ROUTER (replicas
  never see traffic the tier cannot absorb).

Every layer stamps the request flight recorder
(``observability/reqtrace.py``, README "Request tracing"): router
route/retry/re-route/shed decisions, the engine's admission / chunk
scheduling / decode ticks / preemptions, and stream delivery marks all
land on one per-request timeline, so ``tools/request_trace.py`` can
reconstruct any request's causal story across replicas after the fact.
"""
from .router import Router, RouterConfig
from .scheduler import Scheduler, SchedulerConfig
from .speculative import NgramProposer
from .stream import TokenStream

__all__ = ["Router", "RouterConfig", "Scheduler", "SchedulerConfig",
           "NgramProposer", "TokenStream"]
